"""End-to-end causal tracing plane.

The trace plane's contract surface, bottom-up:

- **TraceStore semantics** (unit): critical-path golden trees (self-
  times along the path sum to the wall for nested chains), orphan
  grace -> adoption, deferred-sampling finalize (sample-on-error and
  tail-latency force-keep), span dedupe under replay, bounded
  retention, and the Chrome/Perfetto export envelopes.
- **Tracer propagation** (unit): the sampling roll marks only trace
  ROOTS deferred (deterministic at rate 0.0 / 1.0), and
  ``remote_parent`` links children under the REAL remote span id with
  no fake ``<remote-parent>`` span recorded.
- **Wire shape** (unit): an untraced direct call is the exact 6-tuple
  frame (zero extra bytes); a traced one rides the optional 7th
  element.
- **Cross-process assembly** (integration): a head-routed task trace
  contains the driver submit span, the head's dispatch/resource-scan
  spans, and the worker execute span in ONE tree; a direct actor-call
  stream over a dropped peer connection (seqno replay through the
  head, ledger dedupe) yields exactly one span per executed call; a
  proxied HTTP request with a forced replica_busy retry assembles
  proxy -> router -> failed attempt (verdict) -> retry attempt ->
  replica execute, retrievable by the stable request id, with the
  critical path accounting for the wall time.
- **Edge joins**: 504 deadline answers carry ``X-Request-Id`` so a
  failed request can be joined to its trace.
"""

import itertools
import json
import sys
import threading
import time
import urllib.error
import urllib.request
from collections import deque

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.observability.tracestore import TraceStore
from ray_tpu.util import tracing
from ray_tpu.util.tracing import DEFERRED_ATTR, Tracer


def setup_function(_fn):
    # Tests toggle the process-global tracer; start each one clean.
    tracing.disable()
    tracing.get_tracer().drain_dicts()


def teardown_function(_fn):
    tracing.disable()
    tracing.get_tracer().drain_dicts()


def _span(name, tid, sid, parent, start, end, attrs=None,
          process="test"):
    return {"name": name, "trace_id": tid, "span_id": sid,
            "parent_id": parent, "start": start, "end": end,
            "attributes": dict(attrs or {}), "process": process}


def _walk(node):
    yield node
    for c in node.get("children", ()):
        yield from _walk(c)


# ---------------------------------------------------------------------------
# TraceStore unit semantics
# ---------------------------------------------------------------------------

def test_critical_path_golden_linear_chain():
    """Nested chain root(100ms) > mid(80ms) > leaf(30ms): path follows
    the chain and the per-span self-times sum exactly to the wall."""
    t = 1000.0
    st = TraceStore()
    st.add_spans([
        _span("root", "tr1", "a", None, t, t + 0.100),
        _span("mid", "tr1", "b", "a", t + 0.010, t + 0.090),
        _span("leaf", "tr1", "c", "b", t + 0.020, t + 0.050),
    ], now=t + 0.2)
    tr = st.get_trace("tr1", now=t + 0.2)
    assert tr is not None and tr["complete"]
    assert [p["name"] for p in tr["critical_path"]] == \
        ["root", "mid", "leaf"]
    selfs = {p["name"]: p["self_time_ms"] for p in tr["critical_path"]}
    assert selfs["root"] == pytest.approx(20.0, abs=0.01)
    assert selfs["mid"] == pytest.approx(50.0, abs=0.01)
    assert selfs["leaf"] == pytest.approx(30.0, abs=0.01)
    assert tr["critical_path_self_ms"] == \
        pytest.approx(tr["duration_ms"], rel=1e-6)


def test_critical_path_follows_child_finishing_last():
    """Fan-out: the path descends into the BLOCKING child (latest
    end), and sibling overlap is not double-counted in self-time."""
    t = 2000.0
    st = TraceStore()
    st.add_spans([
        _span("root", "tr2", "a", None, t, t + 0.100),
        _span("fast", "tr2", "b", "a", t + 0.010, t + 0.040),
        _span("slow", "tr2", "c", "a", t + 0.020, t + 0.090),
    ], now=t + 0.2)
    tr = st.get_trace("tr2", now=t + 0.2)
    assert [p["name"] for p in tr["critical_path"]] == ["root", "slow"]
    # root self = 100 - union([10,40]∪[20,90] = [10,90]) = 20ms.
    assert tr["critical_path"][0]["self_time_ms"] == \
        pytest.approx(20.0, abs=0.01)
    assert tr["critical_path_self_ms"] == pytest.approx(90.0, abs=0.05)


def test_orphan_grace_then_adoption():
    t = 3000.0
    st = TraceStore(orphan_grace_s=1.0)
    st.add_spans([
        _span("root", "tr3", "a", None, t, t + 0.05),
        _span("stray", "tr3", "x", "missing-parent", t + 0.01,
              t + 0.02),
    ], now=t)
    # Within grace: incomplete, the stray is pending (maybe its parent
    # is still in flight from another process).
    within = st.get_trace("tr3", now=t + 0.2)
    assert within["complete"] is False
    assert within["pending_orphans"] == 1
    assert within["orphans_adopted"] == 0
    # Grace expired: adopted under the root, tagged, tree complete.
    after = st.get_trace("tr3", now=t + 2.0)
    assert after["complete"] is True
    assert after["orphans_adopted"] == 1
    adopted = [s for s in _walk(after["tree"])
               if s["attributes"].get("orphan")]
    assert [s["name"] for s in adopted] == ["stray"]


def test_deferred_sampling_dropped_at_finalize():
    t = 4000.0
    st = TraceStore(orphan_grace_s=0.5)
    st.add_spans([_span("root", "trd", "a", None, t, t + 0.01,
                        {DEFERRED_ATTR: True})], now=t)
    assert st.get_trace("trd", now=t + 0.1) is not None
    st.add_spans([], now=t + 1.0)       # sweep past the grace window
    assert st.get_trace("trd", now=t + 1.0) is None
    assert st.traces_sampled_out == 1


def test_deferred_trace_kept_on_error():
    t = 5000.0
    st = TraceStore(orphan_grace_s=0.5, sample_on_error=True)
    st.add_spans([
        _span("root", "tre", "a", None, t, t + 0.01,
              {DEFERRED_ATTR: True}),
        _span("boom", "tre", "b", "a", t, t + 0.005,
              {"error": "ValueError"}),
    ], now=t)
    st.add_spans([], now=t + 1.0)
    kept = st.get_trace("tre", now=t + 1.0)
    assert kept is not None and kept["errors"] == ["b"]
    assert st.traces_sampled_out == 0


def test_deferred_trace_kept_on_tail_latency():
    t = 6000.0
    st = TraceStore(orphan_grace_s=0.5, sample_on_error=False,
                    force_sample_ms=50.0)
    st.add_spans([_span("slow", "trs", "a", None, t, t + 0.1,
                        {DEFERRED_ATTR: True})], now=t)
    st.add_spans([_span("fast", "trf", "b", None, t, t + 0.01,
                        {DEFERRED_ATTR: True})], now=t)
    st.add_spans([], now=t + 1.0)
    assert st.get_trace("trs", now=t + 1.0) is not None   # 100ms >= 50
    assert st.get_trace("trf", now=t + 1.0) is None       # 10ms < 50
    assert st.traces_sampled_out == 1


def test_store_dedupes_replayed_spans():
    t = 7000.0
    spans = [_span("root", "trr", "a", None, t, t + 0.01),
             _span("kid", "trr", "b", "a", t, t + 0.005)]
    st = TraceStore()
    st.add_spans(spans, now=t)
    st.add_spans(spans, now=t + 0.1)        # replayed feed: no-op
    assert st.spans_ingested == 2
    assert st.get_trace("trr", now=t + 0.1)["num_spans"] == 2


def test_bounded_retention_evicts_oldest():
    st = TraceStore(max_traces=2, ttl_s=1e9)
    for i, tid in enumerate(("t-old", "t-mid", "t-new")):
        st.add_spans([_span("r", tid, f"s{i}", None,
                            8000.0 + i, 8000.5 + i)], now=8000.0 + i)
    assert st.get_trace("t-old", now=8002.0) is None
    assert st.get_trace("t-mid", now=8002.0) is not None
    assert st.get_trace("t-new", now=8002.0) is not None
    assert st.traces_evicted == 1


def test_trace_export_envelopes():
    t = 9000.0
    st = TraceStore()
    st.add_spans([
        _span("root", "trx", "a", None, t, t + 0.01, {"k": "v"}),
        _span("kid", "trx", "b", "a", t, t + 0.005),
    ], now=t)
    events = st.chrome_trace("trx")
    assert [e["name"] for e in events] == ["root", "kid"]
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in events)
    assert events[0]["args"] == {"k": "v"}
    perfetto = st.perfetto_trace("trx")
    assert perfetto["traceEvents"] == events
    assert perfetto["displayTimeUnit"] == "ms"
    json.dumps(perfetto)                    # must be JSON-serializable


# ---------------------------------------------------------------------------
# Tracer propagation units
# ---------------------------------------------------------------------------

def test_sampling_rate_marks_only_roots_deferred():
    tr = Tracer()
    tr.enable()
    tr.sample_rate = 0.0                    # deterministic: always out
    with tr.span("root") as root:
        with tr.span("child") as child:
            pass
    assert root.attributes.get(DEFERRED_ATTR) is True
    assert DEFERRED_ATTR not in child.attributes

    tr2 = Tracer()
    tr2.enable()
    tr2.sample_rate = 1.0                   # deterministic: always in
    with tr2.span("root") as root2:
        pass
    assert DEFERRED_ATTR not in root2.attributes


def test_remote_parent_links_real_span_id():
    """The propagated context parents children under the REAL remote
    span id — and no fake ``<remote-parent>`` span is ever recorded."""
    tr = Tracer()
    tr.enable()
    with tr.remote_parent(("t" * 16, "p" * 16)):
        assert tr.current_context() == ("t" * 16, "p" * 16)
        with tr.span("child") as s:
            pass
    assert s.trace_id == "t" * 16
    assert s.parent_id == "p" * 16
    names = [sp.name for sp in tr.get_spans()]
    assert names == ["child"]


def test_direct_call_frame_shape_untraced_vs_traced():
    """Zero-extra-bytes contract on the wire: the untraced steady
    state keeps the exact 6-tuple OP_CALL_DIRECT frame; a traced call
    rides the context as an OPTIONAL 7th element, and the unacked
    replay entry carries it either way."""
    from ray_tpu.core import protocol as P
    from ray_tpu.core.worker import _DirectChannel

    ch = _DirectChannel.__new__(_DirectChannel)      # no dial
    ch._cv = threading.Condition()
    ch.dead = False
    ch.window = 64
    ch._seq = itertools.count()
    ch.unacked = {}
    ch._outbox = deque()
    ch._out_ev = threading.Event()

    ch.submit(b"t" * 16, "f", b"args", 1, [b"r0"], [b"n0"])
    frame = ch._outbox.popleft()
    assert frame[0] == P.OP_CALL_DIRECT
    assert len(frame) == 6
    assert ch.unacked[frame[1]][6] is None

    ctx = ("tid0", "sid0")
    ch.submit(b"t" * 16, "f", b"args", 1, [b"r1"], [b"n1"],
              trace_ctx=ctx)
    frame = ch._outbox.popleft()
    assert len(frame) == 7
    assert frame[6] == ctx
    assert ch.unacked[frame[1]][6] == ctx


def test_error_response_carries_request_id():
    from ray_tpu.serve.exceptions import (
        DeploymentOverloadedError,
        RequestDeadlineError,
    )
    from ray_tpu.serve.proxy import error_response

    status, headers, _ = error_response(
        DeploymentOverloadedError("full"), "rid-503")
    assert status == 503
    assert headers["X-Request-Id"] == "rid-503"
    assert headers["Retry-After"]

    status, headers, _ = error_response(
        RequestDeadlineError("late"), "rid-504")
    assert status == 504
    assert headers["X-Request-Id"] == "rid-504"

    status, headers, _ = error_response(ValueError("boom"), "rid-500")
    assert status == 500
    assert headers["X-Request-Id"] == "rid-500"

    _, headers, _ = error_response(ValueError("boom"))
    assert "X-Request-Id" not in headers


# ---------------------------------------------------------------------------
# Cross-process assembly (integration)
# ---------------------------------------------------------------------------

def _poll_trace(rt_obj, tid, pred, deadline_s=20.0):
    end = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < end:
        last = rt_obj.get_trace(tid)
        if last is not None and pred(last):
            return last
        time.sleep(0.2)
    return last


def test_task_trace_assembles_across_head_and_worker(rt):
    """One head-routed task = one tree: driver submit span (root),
    the head's resource-scan + dispatch spans, and the worker's
    execute span — stitched from three processes."""
    tracing.enable()
    try:
        @ray_tpu.remote(num_cpus=1)
        def traced_add(x):
            return x + 1

        assert ray_tpu.get(traced_add.remote(1), timeout=60) == 2
        subs = [s for s in tracing.get_spans()
                if s.name == "submit::traced_add"]
        assert subs, "driver submit span missing"
        tid = subs[-1].trace_id

        rt_obj = ray_tpu.core.api.get_runtime()

        def assembled(t):
            names = {s["name"] for s in _walk(t["tree"])}
            return {"submit::traced_add", "task::traced_add",
                    "head.dispatch"} <= names
        t = _poll_trace(rt_obj, tid, assembled)
        assert t is not None, "trace never assembled"
        names = [s["name"] for s in _walk(t["tree"])]
        assert t["tree"]["name"] == "submit::traced_add"
        assert "task::traced_add" in names
        assert "head.dispatch" in names
        assert "head.resource_scan" in names
        # Everything hangs off the real root — no orphan scars.
        t_done = _poll_trace(rt_obj, tid, lambda x: x["complete"])
        assert t_done["complete"], t_done
        # The same tree is reachable through the state API surface.
        from ray_tpu.util import state as state_api
        via_state = state_api.get_trace(tid)
        assert via_state["trace_id"] == tid
        assert any(r["trace_id"] == tid
                   for r in state_api.list_traces(limit=50))
    finally:
        tracing.disable()


@ray_tpu.remote(num_cpus=0)
class Echo:
    def __init__(self):
        self.order = []
        self.execs = {}

    def ping(self):
        return "pong"

    def f(self, i):
        self.order.append(i)
        self.execs[i] = self.execs.get(i, 0) + 1
        return i * 2

    def drop_peers_and_f(self, i):
        # Sever the direct-call connections from INSIDE the hosting
        # worker with this very call's ack in flight: the caller
        # replays the unacked window through the head.
        self.order.append(i)
        self.execs[i] = self.execs.get(i, 0) + 1
        import ray_tpu.core.worker as W
        if W._direct_server is not None:
            W._direct_server.drop_connections()
        return i * 2

    def stats(self):
        return list(self.order), dict(self.execs)


def _ensure_direct(handle, deadline_s: float = 15.0) -> bool:
    rt = ray_tpu.core.api.get_runtime()
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        before = rt.actor_calls_direct
        ray_tpu.get(handle.ping.remote(), timeout=60)
        if rt.actor_calls_direct > before:
            return True
        time.sleep(0.2)
    return False


def test_direct_actor_replay_emits_no_duplicate_spans(rt):
    """At-most-once tracing across the seqno-replay path: a dropped
    peer connection mid-stream replays the unacked window through the
    head with the ORIGINAL trace context; the callee's ledger answers
    replays without re-executing — so the assembled trace holds
    exactly ONE execute span per call."""
    n = 12
    tracing.enable()
    try:
        @ray_tpu.remote(num_cpus=1)
        def caller(handle, n):
            assert _ensure_direct(handle)
            refs = []
            for i in range(n):
                m = (handle.drop_peers_and_f if i == n // 2
                     else handle.f)
                refs.append(m.remote(i))
            return ray_tpu.get(refs, timeout=120)

        a = Echo.remote()
        ray_tpu.get(a.ping.remote(), timeout=60)
        assert ray_tpu.get(caller.remote(a, n), timeout=180) == \
            [i * 2 for i in range(n)]
        order, execs = ray_tpu.get(a.stats.remote(), timeout=60)
        assert all(v == 1 for v in execs.values()), execs

        subs = [s for s in tracing.get_spans()
                if s.name == "submit::caller"]
        assert subs
        tid = subs[-1].trace_id
        rt_obj = ray_tpu.core.api.get_runtime()

        def all_calls_in(t):
            names = [s["name"] for s in _walk(t["tree"])]
            return (names.count("actor::f") >= n - 1
                    and names.count("actor::drop_peers_and_f") >= 1)
        t = _poll_trace(rt_obj, tid, all_calls_in)
        assert t is not None, "actor-call spans never assembled"
        names = [s["name"] for s in _walk(t["tree"])]
        # Exactly one span per executed call — a replay that re-emitted
        # spans would show as > n-1 / > 1 here.
        assert names.count("actor::f") == n - 1, names
        assert names.count("actor::drop_peers_and_f") == 1, names
    finally:
        tracing.disable()


@pytest.fixture
def serve_rt(rt):
    yield rt
    serve.shutdown()


def _post(url, body, headers=None, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers=headers or {}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_serve_http_retry_assembles_one_trace(serve_rt, tmp_path):
    """The acceptance trace: a proxied request whose first replica
    sheds (forced one-shot ReplicaStoppingError) assembles into ONE
    tree — ingress > router > failed attempt (verdict=replica_busy) >
    retry attempt > replica execute — retrievable by the stable
    request id, with the critical path accounting for the wall."""
    from ray_tpu.core.config import env_overrides

    flag = str(tmp_path / "failed_once")
    http_port = 18761
    rid = "trace-join-rid-1"

    with env_overrides(trace_serve_requests=True):
        @serve.deployment(num_replicas=2)
        class FlakyOnce:
            def __call__(self, x):
                import os
                import time as _t

                from ray_tpu.serve.exceptions import (
                    ReplicaStoppingError,
                )
                if not os.path.exists(flag):
                    with open(flag, "w") as f:
                        f.write("1")
                    raise ReplicaStoppingError("test one-shot drain")
                _t.sleep(0.5)
                return {"ok": x}

        serve.run(FlakyOnce.bind(), http_port=http_port)
        status, _, body = _post(f"http://127.0.0.1:{http_port}/",
                                {"v": 1}, {"X-Request-Id": rid})
        assert status == 200, body

        rt_obj = ray_tpu.core.api.get_runtime()

        def find_trace():
            for row in rt_obj.list_traces(limit=50):
                t = rt_obj.get_trace(row["trace_id"])
                if t and t["root"]["name"] == "serve.ingress" and \
                        t["root"]["attributes"].get(
                            "request_id") == rid:
                    return t
            return None

        t = None
        end = time.monotonic() + 20.0
        while time.monotonic() < end:
            t = find_trace()
            if t is not None and t["complete"] and any(
                    s["name"] == "serve.replica.execute"
                    for s in _walk(t["tree"])):
                break
            time.sleep(0.2)
        assert t is not None, "serve trace never assembled"

        spans = list(_walk(t["tree"]))
        names = [s["name"] for s in spans]
        assert t["tree"]["name"] == "serve.ingress"
        assert "serve.router" in names
        attempts = [s for s in spans if s["name"] == "serve.attempt"]
        assert len(attempts) >= 2, names
        verdicts = [s["attributes"].get("verdict") for s in attempts]
        assert "replica_busy" in verdicts, verdicts
        # One successful execute; the failed attempt's execute span
        # (if its replica got far enough to open one) is error-tagged.
        executes = [s for s in spans
                    if s["name"] == "serve.replica.execute"]
        clean = [s for s in executes
                 if "error" not in s["attributes"]]
        assert len(clean) == 1, [
            (s["name"], s["attributes"]) for s in executes]
        assert t["complete"], t

        # Critical path: follows the RETRY attempt (the failed one is
        # off-path), so its self-times cover the wall minus that
        # failed attempt's duration, within 10% of the wall.
        failed = [a for a in attempts
                  if a["attributes"].get("verdict")]
        off_path_ms = sum(a["duration_ms"] for a in failed)
        cp = t["critical_path_self_ms"]
        dur = t["duration_ms"]
        assert cp <= 1.05 * dur, (cp, dur)
        assert cp >= dur - off_path_ms - 0.10 * dur, \
            (cp, dur, off_path_ms)
        path_names = [p["name"] for p in t["critical_path"]]
        assert path_names[:2] == ["serve.ingress", "serve.router"]
        assert "serve.replica.execute" in path_names

        # The same trace must come back through the other two
        # acceptance surfaces: the dashboard endpoint and the CLI.
        from ray_tpu.dashboard.head import start_dashboard
        dash = start_dashboard(port=0, runtime=rt_obj)
        try:
            rows = json.loads(urllib.request.urlopen(
                dash.url + "/api/v1/traces", timeout=30).read())
            assert any(r["trace_id"] == t["trace_id"] for r in rows)
            one = json.loads(urllib.request.urlopen(
                dash.url + f"/api/v1/traces/{t['trace_id']}",
                timeout=30).read())
            assert one["tree"]["name"] == "serve.ingress"
            chrome = json.loads(urllib.request.urlopen(
                dash.url + f"/api/v1/traces/{t['trace_id']}"
                "?format=chrome", timeout=30).read())
            assert any(e.get("name") == "serve.replica.execute"
                       for e in chrome)
        finally:
            dash.stop()

        import io
        from ray_tpu.scripts.cli import main as cli_main
        buf = io.StringIO()
        old = sys.stdout
        sys.stdout = buf
        try:
            assert cli_main(["trace", t["trace_id"]]) == 0
            assert cli_main(["traces", "--slowest"]) == 0
        finally:
            sys.stdout = old
        out = buf.getvalue()
        assert "serve.ingress" in out
        assert "verdict=replica_busy" in out
        assert "critical path" in out
        assert t["trace_id"] in out


def test_http_deadline_504_carries_request_id(serve_rt):
    http_port = 18762
    rid = "rid-504-join"

    @serve.deployment(num_replicas=1)
    class Slow:
        def __call__(self, x):
            time.sleep(1.5)
            return {"ok": True}

    serve.run(Slow.bind(), http_port=http_port)
    status, headers, body = _post(
        f"http://127.0.0.1:{http_port}/", {"v": 1},
        {"X-Request-Timeout-S": "0.2", "X-Request-Id": rid})
    assert status == 504, body
    assert headers.get("X-Request-Id") == rid
