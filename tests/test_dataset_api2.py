"""Round-5 Dataset API surface batch (reference: ray.data.Dataset —
aggregate/splits/sampling/refs-exports/writers/torch+tf exports).
"""

import os
import sqlite3

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data
from ray_tpu.data.aggregate import (
    AggregateFn, Count, Max, Mean, Min, Std, Sum,
)


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def _ds(rt, n=20, parallelism=4):
    return data.range(n, parallelism=parallelism).map(
        lambda r: {"id": r["id"], "x": float(r["id"]) * 0.5})


# -- aggregate ----------------------------------------------------------


def test_dataset_aggregate(rt):
    ds = _ds(rt)
    out = ds.aggregate(Count(), Sum("id"), Mean("x"), Min("id"),
                       Max("id"), Std("x"))
    assert out["count()"] == 20
    assert out["sum(id)"] == sum(range(20))
    assert out["mean(x)"] == pytest.approx(np.mean(np.arange(20) * 0.5))
    assert out["min(id)"] == 0 and out["max(id)"] == 19
    assert out["std(x)"] == pytest.approx(
        np.std(np.arange(20) * 0.5, ddof=1))


def test_aggregate_empty_blocks_and_std_stability(rt):
    # filter empties 3 of 4 blocks: Min/Max must skip them
    ds = data.range(20, parallelism=4).filter(lambda r: r["id"] < 5)
    out = ds.aggregate(Count(), Min("id"), Max("id"))
    assert out == {"count()": 5, "min(id)": 0, "max(id)": 4}
    # Welford merge: stddev around a huge mean must not cancel
    big = data.from_items([{"x": 1e8 + i} for i in range(5)])
    got = big.aggregate(Std("x"))["std(x)"]
    assert got == pytest.approx(np.std(1e8 + np.arange(5), ddof=1),
                                rel=1e-9)


def test_dataset_aggregate_custom_fn(rt):
    prod = AggregateFn(
        init=lambda: 1.0,
        accumulate_block=lambda a, col: a * float(np.prod(col)),
        merge=lambda a, b: a * b,
        on="x", name="prod(x)")
    out = data.from_items([{"x": 2.0}, {"x": 3.0}, {"x": 4.0}]).aggregate(
        prod)
    assert out["prod(x)"] == pytest.approx(24.0)


def test_dataset_aggregate_type_error(rt):
    with pytest.raises(TypeError, match="AggregateFn"):
        _ds(rt).aggregate("sum")


def test_groupby_aggregate(rt):
    ds = data.from_items([
        {"g": i % 3, "v": float(i)} for i in range(12)])
    rows = sorted(ds.groupby("g").aggregate(Count(), Sum("v")).take_all(),
                  key=lambda r: r["g"])
    assert [r["g"] for r in rows] == [0, 1, 2]
    assert all(r["count()"] == 4 for r in rows)
    for r in rows:
        assert r["sum(v)"] == sum(float(i) for i in range(12)
                                  if i % 3 == r["g"])


# -- splits / sampling --------------------------------------------------


def test_split_at_indices(rt):
    parts = _ds(rt).split_at_indices([5, 5, 17])
    assert [p.count() for p in parts] == [5, 0, 12, 3]
    assert [r["id"] for r in parts[2].take_all()] == list(range(5, 17))
    # empty split keeps the schema
    assert parts[1].columns() == ["id", "x"]


def test_split_at_indices_validation(rt):
    with pytest.raises(ValueError, match="sorted"):
        _ds(rt).split_at_indices([7, 3])
    with pytest.raises(ValueError, match="non-negative"):
        _ds(rt).split_at_indices([-1])


def test_split_proportionately(rt):
    parts = data.range(100, parallelism=5).split_proportionately(
        [0.1, 0.3])
    assert [p.count() for p in parts] == [10, 30, 60]
    with pytest.raises(ValueError):
        data.range(10).split_proportionately([0.5, 0.6])


def test_train_test_split(rt):
    train, test = data.range(50, parallelism=5).train_test_split(0.2)
    assert train.count() == 40 and test.count() == 10
    assert [r["id"] for r in test.take_all()] == list(range(40, 50))
    train2, test2 = data.range(50, parallelism=5).train_test_split(
        7, shuffle=True, seed=3)
    assert train2.count() == 43 and test2.count() == 7
    all_ids = sorted(r["id"] for r in train2.take_all()) + \
        sorted(r["id"] for r in test2.take_all())
    assert sorted(all_ids) == list(range(50))


def test_randomize_block_order(rt):
    ds = data.range(40, parallelism=8)
    shuf = ds.randomize_block_order(seed=5)
    ids = [r["id"] for r in shuf.take_all()]
    assert sorted(ids) == list(range(40))
    assert ids != list(range(40))  # 8! orders; seed 5 is not identity
    # within a block, row order is preserved
    first_block = ids[:5]
    assert first_block == list(range(first_block[0], first_block[0] + 5))


def test_random_sample_blocks_draw_independently(rt):
    # 4 equal-sized blocks of DIFFERENT content: with a fixed seed the
    # per-block masks must differ (regression: a bare default_rng(seed)
    # gave equal-sized blocks identical masks)
    ds = data.range(400, parallelism=4).random_sample(0.5, seed=7)
    picked = [r["id"] for r in ds.take_all()]
    per_block = [
        {i - 100 * b for i in picked if 100 * b <= i < 100 * (b + 1)}
        for b in range(4)]
    assert not all(s == per_block[0] for s in per_block[1:])


def test_random_sample(rt):
    ds = data.range(400, parallelism=4)
    n = ds.random_sample(0.5, seed=11).count()
    assert 100 < n < 300
    assert ds.random_sample(0.0).count() == 0
    assert ds.random_sample(1.0).count() == 400
    with pytest.raises(ValueError):
        ds.random_sample(1.5)


# -- inspection ---------------------------------------------------------


def test_size_bytes_show_copy_iterator(rt, capsys):
    ds = _ds(rt)
    assert ds.size_bytes() > 0
    ds.show(3)
    out = capsys.readouterr().out
    assert out.count("\n") == 3 and "'id'" in out
    c = ds.copy().filter(lambda r: r["id"] < 5)
    assert c.count() == 5 and ds.count() == 20
    it = ds.iterator()
    got = sum(len(b["id"]) for b in it.iter_batches(batch_size=6))
    assert got == 20


# -- refs exports -------------------------------------------------------


def test_to_refs_exports(rt):
    ds = _ds(rt, n=8, parallelism=2)
    arrow_refs = ds.to_arrow_refs()
    assert sum(t.num_rows for t in ray_tpu.get(arrow_refs)) == 8
    pd_refs = ds.to_pandas_refs()
    assert sum(len(df) for df in ray_tpu.get(pd_refs)) == 8
    npy = ray_tpu.get(ds.to_numpy_refs(column="id"))
    assert np.concatenate(npy).tolist() == list(range(8))
    dicts = ray_tpu.get(ds.to_numpy_refs())
    assert set(dicts[0]) == {"id", "x"}
    # round-trip through the from_*_refs constructors
    assert data.from_arrow_refs(arrow_refs).count() == 8


# -- writers ------------------------------------------------------------


def test_write_numpy(rt, tmp_path):
    p = str(tmp_path / "npy")
    _ds(rt, n=10, parallelism=2).write_numpy(p, column="x")
    parts = sorted(os.listdir(p))
    assert parts == ["part-00000.npy", "part-00001.npy"]
    got = np.concatenate([np.load(f"{p}/{f}") for f in parts])
    assert got.tolist() == [i * 0.5 for i in range(10)]
    with pytest.raises(ValueError, match="nope"):
        _ds(rt).write_numpy(p, column="nope")


def test_write_sql_roundtrip(rt, tmp_path):
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("create table t (id int, x real)")
    conn.commit()
    conn.close()
    _ds(rt, n=6).write_sql("insert into t values (?, ?)",
                           lambda: sqlite3.connect(db))
    back = data.read_sql("select id, x from t order by id",
                         lambda: sqlite3.connect(db))
    assert [r["id"] for r in back.take_all()] == list(range(6))


def test_write_webdataset_roundtrip(rt, tmp_path):
    p = str(tmp_path / "wds")
    ds = data.from_items([
        {"txt": f"hello{i}", "cls": i} for i in range(5)])
    ds.write_webdataset(p)
    back = data.read_webdataset(f"{p}/*.tar")
    rows = sorted(back.take_all(), key=lambda r: r["cls"])
    assert [r["cls"] for r in rows] == list(range(5))  # int parsed
    assert rows[2]["txt"] == b"hello2"  # bytes by contract


def test_write_images_roundtrip(rt, tmp_path):
    p = str(tmp_path / "imgs")
    arr = (np.arange(4 * 6 * 3, dtype=np.uint8)
           .reshape(4, 6, 3))
    data.from_items([{"image": arr}, {"image": arr[::-1].copy()}]
                    ).write_images(p)
    assert sorted(os.listdir(p)) == ["img-000000.png", "img-000001.png"]
    back = data.read_images(f"{p}/*.png")
    got = sorted(back.take_all(), key=lambda r: r["path"])
    assert np.array_equal(got[0]["image"], arr)


def test_write_bigquery(rt):
    calls = []

    def transport(method, url, params, body):
        calls.append((method, url, body))
        return {}

    _ds(rt, n=4, parallelism=2).write_bigquery(
        "proj", "d.t", transport=transport)
    assert len(calls) == 2
    method, url, body = calls[0]
    assert method == "POST" and url.endswith("/tables/t/insertAll")
    assert body["rows"][0]["json"]["id"] == 0
    bad = lambda m, u, p, b: {"insertErrors": [{"index": 0}]}  # noqa: E731
    with pytest.raises(RuntimeError, match="insertAll"):
        _ds(rt, n=2).write_bigquery("proj", "d.t", transport=bad)


def test_write_datasink(rt):
    class Sink(data.Datasink):
        def __init__(self):
            self.events = []

        def on_write_start(self):
            self.events.append("start")

        def write(self, block):
            self.events.append(block.num_rows)

        def on_write_complete(self):
            self.events.append("done")

    s = Sink()
    _ds(rt, n=10, parallelism=2).write_datasink(s)
    assert s.events[0] == "start" and s.events[-1] == "done"
    assert sum(e for e in s.events if isinstance(e, int)) == 10

    class FailSink(Sink):
        def write(self, block):
            raise RuntimeError("sink boom")

        def on_write_failed(self, error):
            self.events.append(f"failed:{error}")

    f = FailSink()
    with pytest.raises(RuntimeError, match="sink boom"):
        _ds(rt).write_datasink(f)
    assert any(str(e).startswith("failed:") for e in f.events)


# -- framework exports --------------------------------------------------


def test_to_torch(rt):
    import torch
    tds = _ds(rt, n=12, parallelism=2).to_torch(
        label_column="x", batch_size=4)
    batches = list(tds)
    assert len(batches) == 3
    feats, label = batches[0]
    assert isinstance(label, torch.Tensor) and label.shape[0] == 4
    assert set(feats) == {"id"}
    plain = list(_ds(rt, n=4).to_torch(batch_size=2))
    assert set(plain[0]) == {"id", "x"}


def test_tf_exports(rt):
    tf = pytest.importorskip("tensorflow")
    batches = list(_ds(rt, n=8, parallelism=2).iter_tf_batches(
        batch_size=4))
    assert len(batches) == 2
    assert isinstance(batches[0]["x"], tf.Tensor)
    assert batches[0]["x"].shape[0] == 4
    tfds = _ds(rt, n=8, parallelism=2).to_tf("id", "x", batch_size=4)
    feats, labels = next(iter(tfds))
    assert feats.shape[0] == 4 and labels.dtype == tf.float64
