"""Client mode: a separate process connects to a live head with
``init(address=...)`` and uses the full API (Ray Client analog,
python/ray/util/client/)."""

import os
import subprocess
import sys
import textwrap

import ray_tpu

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


CLIENT_SCRIPT = textwrap.dedent("""
    import sys
    import ray_tpu

    ray_tpu.init(address=sys.argv[1])

    @ray_tpu.remote
    def square(x):
        return x * x

    assert ray_tpu.get(square.remote(7), timeout=120) == 49

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0
        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=120) == 1
    assert ray_tpu.get(c.incr.remote(), timeout=120) == 2

    # objects put by the client are readable on the cluster
    ref = ray_tpu.put({"k": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"k": [1, 2, 3]}

    # resources visible
    assert ray_tpu.cluster_resources().get("CPU", 0) >= 1
    print("CLIENT_OK")
""")


def _run_client(address: str) -> str:
    out = subprocess.run(
        [sys.executable, "-c", CLIENT_SCRIPT, address],
        capture_output=True, text=True, timeout=300,
        cwd=REPO_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


def test_client_connects_by_address(rt):
    addr = ray_tpu.client_address()
    assert "CLIENT_OK" in _run_client(addr)


def test_client_connects_auto(rt):
    assert "CLIENT_OK" in _run_client("auto")


def test_client_sees_named_actor(rt):
    @ray_tpu.remote
    class Svc:
        def val(self):
            return 41

    Svc.options(name="shared_svc").remote()
    script = textwrap.dedent("""
        import sys
        import ray_tpu
        ray_tpu.init(address=sys.argv[1])
        h = ray_tpu.get_actor("shared_svc")
        assert ray_tpu.get(h.val.remote(), timeout=120) == 41
        print("NAMED_OK")
    """)
    out = subprocess.run(
        [sys.executable, "-c", script, ray_tpu.client_address()],
        capture_output=True, text=True, timeout=300,
        cwd=REPO_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "NAMED_OK" in out.stdout
