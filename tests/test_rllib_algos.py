"""DQN / IMPALA / SAC algorithm tests on toy envs.

Reference analog: rllib/algorithms/{dqn,impala,sac}/tests — smoke +
learning tests on small envs.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import DQNConfig, ImpalaConfig, SACConfig
from ray_tpu.rllib.dqn import DQNHyperparams, DQNLearner, ReplayBuffer
from ray_tpu.rllib.env_runner import Episode
from ray_tpu.rllib.impala import ImpalaHyperparams, ImpalaLearner
from ray_tpu.rllib.models import SquashedGaussianActor


class ChainEnv:
    """Walk right along a chain of N one-hot states; +1 at the end,
    -0.01 per step; truncates after 30 steps."""

    N = 8

    def __init__(self):
        self.pos = 0
        self.t = 0

    def _obs(self):
        o = np.zeros(self.N, np.float32)
        o[self.pos] = 1.0
        return o

    def reset(self, seed=None):
        self.pos, self.t = 0, 0
        return self._obs(), {}

    def step(self, action):
        self.t += 1
        self.pos = max(0, min(self.N - 1,
                              self.pos + (1 if action == 1 else -1)))
        term = self.pos == self.N - 1
        reward = 1.0 if term else -0.01
        trunc = self.t >= 30 and not term
        return self._obs(), reward, term, trunc, {}


class Point1DEnv:
    """Continuous: drive x to 0; reward -x^2; 16-step episodes."""

    def __init__(self):
        self.x = 0.0
        self.t = 0
        self.rng = np.random.default_rng(0)

    def reset(self, seed=None):
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.x = float(self.rng.uniform(-1.0, 1.0))
        self.t = 0
        return np.array([self.x], np.float32), {}

    def step(self, action):
        a = float(np.clip(np.asarray(action).reshape(-1)[0], -1, 1))
        self.x = float(np.clip(self.x + 0.25 * a, -2.0, 2.0))
        self.t += 1
        reward = -self.x ** 2
        trunc = self.t >= 16
        return np.array([self.x], np.float32), reward, False, trunc, {}


# ---------- units ----------

def test_replay_buffer_circular():
    buf = ReplayBuffer(capacity=8, obs_dim=2)
    ep = Episode(
        obs=[np.full(2, i, np.float32) for i in range(12)],
        actions=list(range(12)), rewards=[1.0] * 12,
        logps=[0.0] * 12, values=[0.0] * 12, terminated=True,
        final_obs=np.full(2, 12, np.float32))
    added = buf.add_episodes([ep])
    assert added == 12
    assert buf.size == 8              # capacity-bounded
    batch = buf.sample(16, np.random.default_rng(0))
    assert batch["obs"].shape == (16, 2)
    # next_obs must be obs shifted by one step
    assert np.all(batch["next_obs"][:, 0] == batch["obs"][:, 0] + 1)


def test_dqn_learner_reduces_td_error():
    hp = DQNHyperparams(lr=5e-3)
    learner = DQNLearner({"obs_dim": 4, "num_actions": 2}, hp, seed=0)
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.standard_normal((64, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, 64).astype(np.int32),
        "rewards": rng.standard_normal(64).astype(np.float32),
        "next_obs": rng.standard_normal((64, 4)).astype(np.float32),
        "dones": np.zeros(64, np.float32),
    }
    first = learner.update(batch)["loss"]
    for _ in range(50):
        last = learner.update(batch)["loss"]
    assert last < first               # fits the fixed batch


def test_vtrace_on_policy_rho_is_one():
    """When behavior logps equal the target policy's, importance
    weights must be 1 (the v-trace invariant)."""
    learner = ImpalaLearner({"obs_dim": 3, "num_actions": 2},
                            ImpalaHyperparams(), max_seq_len=8, seed=0)
    rng = np.random.default_rng(1)
    obs = rng.standard_normal((6, 3)).astype(np.float32)
    import jax
    import jax.numpy as jnp
    logits, _ = learner.model.apply({"params": learner.params},
                                    jnp.asarray(obs))
    logp_all = np.asarray(jax.nn.log_softmax(logits))
    actions = [int(rng.integers(0, 2)) for _ in range(6)]
    ep = Episode(
        obs=list(obs), actions=actions,
        rewards=[1.0] * 6,
        logps=[float(logp_all[t, a]) for t, a in enumerate(actions)],
        values=[0.0] * 6, terminated=True,
        final_obs=obs[-1])
    metrics = learner.update_from_episodes([ep])
    assert metrics["mean_rho"] == pytest.approx(1.0, abs=1e-4)
    assert np.isfinite(metrics["total_loss"])


def test_squashed_gaussian_bounds_and_logp():
    import jax
    import jax.numpy as jnp
    mu = jnp.zeros((32, 2))
    log_std = jnp.zeros((32, 2))
    a, logp = SquashedGaussianActor.sample(mu, log_std,
                                           jax.random.key(0))
    assert np.all(np.abs(np.asarray(a)) <= 1.0)
    assert np.all(np.isfinite(np.asarray(logp)))


# ---------- learning e2e ----------

@pytest.mark.slow
def test_dqn_learns_chain(rt):
    algo = (DQNConfig()
            .environment(ChainEnv, obs_dim=8, num_actions=2,
                         hidden=(32, 32))
            .env_runners(1)
            .training(learning_starts=200, train_batch_size=64,
                      num_gradient_steps=4, epsilon_decay_iters=10,
                      target_update_freq=1, lr=5e-4)
            .build())
    try:
        rewards = []
        for _ in range(25):
            r = algo.train()
            rewards.append(r["episode_reward_mean"])
        late = np.nanmean(rewards[-5:])
        # Optimal ≈ 0.94 (7 steps × -0.01 + 1); random ≪ that.
        assert late > 0.6, f"DQN failed to learn: {rewards}"
    finally:
        algo.stop()


@pytest.mark.slow
def test_impala_learns_chain(rt):
    algo = (ImpalaConfig()
            .environment(ChainEnv, obs_dim=8, num_actions=2,
                         hidden=(32, 32))
            .env_runners(2)
            .training(lr=5e-3, entropy_coeff=0.005, optimizer="adam")
            .build())
    try:
        rewards = []
        for _ in range(35):
            r = algo.train()
            rewards.append(r["episode_reward_mean"])
        late = np.nanmean(rewards[-5:])
        assert late > 0.5, f"IMPALA failed to learn: {rewards}"
    finally:
        algo.stop()


@pytest.mark.slow
def test_sac_learns_point1d(rt):
    algo = (SACConfig()
            .environment(Point1DEnv, obs_dim=1, action_dim=1,
                         hidden=(32, 32))
            .env_runners(1)
            .training(learning_starts=256, train_batch_size=128,
                      num_gradient_steps=16)
            .build())
    try:
        rewards = []
        for _ in range(25):
            r = algo.train()
            rewards.append(r["episode_reward_mean"])
        early = np.nanmean(rewards[:5])
        late = np.nanmean(rewards[-5:])
        assert late > early, f"SAC did not improve: {rewards}"
        assert late > -3.0, f"SAC final reward too low: {rewards}"
    finally:
        algo.stop()


def test_bc_learns_from_offline_data(rt):
    """BC from a ray_tpu.data Dataset of expert (obs, action) pairs:
    accuracy on the expert policy rises (offline RL entry point,
    reference: rllib/algorithms/bc)."""
    from ray_tpu import data as rdata
    from ray_tpu.rllib import BCConfig

    rng = np.random.default_rng(0)
    obs = rng.standard_normal((512, 4)).astype(np.float32)
    # Expert: action = argmax of a fixed linear policy.
    w = rng.standard_normal((4, 3)).astype(np.float32)
    actions = np.argmax(obs @ w, axis=1).astype(np.int64)
    ds = rdata.from_numpy({"obs": obs, "action": actions},
                          parallelism=4)

    algo = (BCConfig()
            .environment(obs_dim=4, num_actions=3, hidden=(32, 32))
            .offline_data(ds)
            .training(lr=3e-3, num_gradient_steps=32)
            .build())
    for _ in range(7):
        m = algo.train()
    assert m["accuracy"] > 0.9, m
    assert m["num_samples"] == 512


@pytest.mark.slow
def test_appo_learns_chain(rt):
    """APPO: PPO clipped surrogate on the IMPALA architecture
    (reference: rllib/algorithms/appo)."""
    from ray_tpu.rllib import APPOConfig

    algo = (APPOConfig()
            .environment(ChainEnv, obs_dim=8, num_actions=2,
                         hidden=(32, 32))
            .env_runners(2)
            .training(lr=5e-3, entropy_coeff=0.005,
                      broadcast_interval=2)
            .build())
    try:
        rewards = []
        for _ in range(35):
            r = algo.train()
            rewards.append(r["episode_reward_mean"])
        late = np.nanmean(rewards[-5:])
        assert late > 0.5, f"APPO failed to learn: {rewards}"
    finally:
        algo.stop()


def test_marwil_learns_from_offline_returns(rt):
    """MARWIL: advantage-weighted imitation prefers high-return
    actions over a mediocre behavior policy (reference:
    rllib/algorithms/marwil)."""
    from ray_tpu import data as rdata
    from ray_tpu.rllib import MARWILConfig

    rng = np.random.default_rng(1)
    # Behavior data: half expert (action=argmax, high return), half
    # anti-expert (action=argmin, low return). MARWIL should imitate
    # the expert side because of the advantage weighting.
    obs = rng.standard_normal((512, 4)).astype(np.float32)
    w = rng.standard_normal((4, 3)).astype(np.float32)
    expert = np.argmax(obs @ w, axis=1).astype(np.int64)
    anti = np.argmin(obs @ w, axis=1).astype(np.int64)
    take_expert = rng.random(512) < 0.5
    actions = np.where(take_expert, expert, anti)
    returns = np.where(take_expert, 1.0, -1.0).astype(np.float32)
    ds = rdata.from_numpy(
        {"obs": obs, "action": actions, "return": returns},
        parallelism=4)

    algo = (MARWILConfig()
            .environment(obs_dim=4, num_actions=3, hidden=(32, 32))
            .offline_data(ds)
            .training(lr=3e-3, beta=2.0, num_gradient_steps=32)
            .build())
    for _ in range(8):
        m = algo.train()
    # Greedy policy should match the EXPERT on most states, despite
    # only half the data being expert.
    import jax
    import jax.numpy as jnp
    logits, _ = algo.learner.model.apply(
        {"params": algo.learner.params}, jnp.asarray(obs))
    pred = np.asarray(jnp.argmax(logits, axis=1))
    acc_expert = (pred == expert).mean()
    assert acc_expert > 0.75, f"expert match only {acc_expert:.2f}"


def test_marwil_beta_zero_is_bc(rt):
    from ray_tpu.rllib.marwil import (
        MARWILHyperparams, MARWILLearner, returns_from_rewards,
    )

    r = returns_from_rewards([1.0, 1.0, 1.0], [False, False, True],
                             gamma=0.5)
    np.testing.assert_allclose(r, [1.75, 1.5, 1.0])

    learner = MARWILLearner(
        {"obs_dim": 4, "num_actions": 3, "hidden": (16,)},
        MARWILHyperparams(beta=0.0), seed=0)
    rng = np.random.default_rng(0)
    batch = {"obs": rng.standard_normal((32, 4)).astype(np.float32),
             "action": rng.integers(0, 3, 32),
             "return": rng.standard_normal(32).astype(np.float32)}
    m = learner.update(batch)
    # beta=0 -> every weight is exp(0)=1 (pure BC).
    assert abs(m["mean_weight"] - 1.0) < 1e-5


@pytest.mark.slow
def test_cql_learns_point1d_offline(rt):
    """CQL from logged transitions only: the learned policy improves
    on x->0 control without ever touching the env during training
    (reference: rllib/algorithms/cql)."""
    from ray_tpu import data as rdata
    from ray_tpu.rllib import CQLConfig

    # Log transitions from a mediocre-but-covering behavior policy:
    # noisy proportional control.
    rng = np.random.default_rng(0)
    env = Point1DEnv()
    obs_l, act_l, rew_l, next_l, done_l = [], [], [], [], []
    o, _ = env.reset(seed=0)
    for t in range(4096):
        a = np.clip(-0.8 * o[0] + rng.normal() * 0.7, -1, 1)
        no, r, term, trunc, _ = env.step([a])
        obs_l.append(o); act_l.append([a]); rew_l.append(r)
        next_l.append(no); done_l.append(float(term))
        o = no
        if term or trunc:
            o, _ = env.reset(seed=t)
    ds = rdata.from_numpy({
        "obs": np.asarray(obs_l, np.float32),
        "action": np.asarray(act_l, np.float32),
        "reward": np.asarray(rew_l, np.float32),
        "next_obs": np.asarray(next_l, np.float32),
        "done": np.asarray(done_l, np.float32)}, parallelism=4)

    algo = (CQLConfig()
            .environment(obs_dim=1, action_dim=1, hidden=(32, 32))
            .offline_data(ds)
            .training(train_batch_size=256, num_gradient_steps=32,
                      min_q_weight=1.0)
            .build())
    for _ in range(10):
        m = algo.train()
    assert "cql_penalty" in m

    # Evaluate the learned deterministic policy in the live env.
    import jax
    import jax.numpy as jnp

    def act(o):
        mu, _ = algo.learner.actor.apply(
            {"params": algo.learner.actor_params},
            jnp.asarray(o, jnp.float32)[None])
        return np.asarray(jnp.tanh(mu))[0]

    total = 0.0
    for ep in range(5):
        env = Point1DEnv()
        o, _ = env.reset(seed=100 + ep)
        done = False
        while not done:
            o, r, term, trunc, _ = env.step(act(o))
            total += r
            done = term or trunc
    mean_ep = total / 5
    # Random policy scores ~-6; decent control > -2.5.
    assert mean_ep > -2.5, f"CQL policy too weak: {mean_ep:.2f}"


def test_algorithm_evaluate_full_episodes_only(rt):
    """Algorithm.evaluate (reference: evaluation EnvRunners): reward
    stats over COMPLETE episodes — tails of episodes begun during
    training sampling must not count (they'd undercount reward)."""
    class FixedRewardEnv:
        def __init__(self):
            self.t = 0

        def reset(self, seed=None):
            self.t = 0
            return np.zeros(2, np.float32), {}

        def step(self, action):
            self.t += 1
            return (np.zeros(2, np.float32), 1.0, self.t >= 5,
                    False, {})

    algo = (DQNConfig()
            .environment(FixedRewardEnv, obs_dim=2, num_actions=2)
            .build())
    algo.train()          # leaves runners mid-episode
    ev = algo.evaluate(num_episodes=6)["evaluation"]
    # every complete episode is exactly 5 steps of +1
    assert ev["episodes"] == 6
    assert ev["episode_reward_mean"] == 5.0, ev
    assert ev["episode_len_mean"] == 5.0
    algo.stop()


def test_evaluate_stitches_episodes_longer_than_a_round(rt):
    """Episodes longer than one 256-step sample round span several
    chunks; the per-runner stitcher must count them exactly once with
    the FULL reward (a naive chunk filter would never count them and
    return NaN)."""
    class LongEnv:
        def __init__(self):
            self.t = 0

        def reset(self, seed=None):
            self.t = 0
            return np.zeros(2, np.float32), {}

        def step(self, action):
            self.t += 1
            return (np.zeros(2, np.float32), 1.0, self.t >= 400,
                    False, {})

    algo = (DQNConfig()
            .environment(LongEnv, obs_dim=2, num_actions=2)
            .env_runners(1)
            .build())
    ev = algo.evaluate(num_episodes=2)["evaluation"]
    assert ev["episodes"] == 2, ev
    assert ev["episode_reward_mean"] == 400.0, ev
    assert ev["episode_len_mean"] == 400.0
    algo.stop()
