"""Owner-based object directory (reference:
src/ray/object_manager/ownership_based_object_directory.cc): put ids
minted by node daemons embed the owner's tag, so any process resolves
their location as a function of the id — the head's location table is
bootstrap/fallback only. Steady-state cross-node gets must not read
the head directory (locate_calls counter-asserted, the same pattern as
test_p2p_transfer's _relay_chunks)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.ids import ObjectID, owner_tag_of
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
)


def test_owned_id_roundtrip_and_parse():
    tag = owner_tag_of("node_0001_abcd1234")
    oid = ObjectID.for_owned_put(tag)
    assert oid.owner_tag() == tag
    assert oid.is_put_object()
    # Non-owned forms parse as not-owned.
    assert ObjectID.for_put(7).owner_tag() is None
    assert ObjectID.for_put(7).is_put_object()
    # Distinct mints are distinct.
    assert ObjectID.for_owned_put(tag) != oid


@pytest.fixture
def two_nodes():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    na = cluster.add_node(num_cpus=1)
    nb = cluster.add_node(num_cpus=1)
    yield cluster, na, nb
    cluster.shutdown()


def _affinity(node):
    return NodeAffinitySchedulingStrategy(node.node_id, soft=False)


def test_cross_node_get_skips_head_directory(two_nodes):
    cluster, na, nb = two_nodes
    rt = ray_tpu.core.api.get_runtime()

    @ray_tpu.remote(num_cpus=1)
    def produce():
        arr = np.arange(2_000_000, dtype=np.float64)   # 16 MB
        return [ray_tpu.put(arr)]      # nested ref: stays node-local

    @ray_tpu.remote(num_cpus=1)
    def consume(box):
        return float(ray_tpu.get(box[0])[1_234_567])

    [ref] = ray_tpu.get(produce.options(
        scheduling_strategy=_affinity(nb)).remote(), timeout=60)
    # The id itself names the owner.
    assert ref.id.owner_tag() == owner_tag_of(nb.node_id)

    # Steady state: consumer on A pulls from owner B with ZERO head
    # directory reads (owner map was pushed at registration).
    locate0 = rt.locate_calls
    out = ray_tpu.get(consume.options(
        scheduling_strategy=_affinity(na)).remote([ref]), timeout=60)
    assert out == 1_234_567.0
    assert rt.locate_calls == locate0, \
        "cross-node get read the head directory"


def test_head_table_loss_does_not_lose_owned_locations(two_nodes):
    """The head's _obj_locations entry is only a bootstrap: dropping
    it (what a head restart loses before owners re-report) must not
    break resolution — the owner still serves the object."""
    cluster, na, nb = two_nodes
    rt = ray_tpu.core.api.get_runtime()

    @ray_tpu.remote(num_cpus=1)
    def produce():
        arr = np.arange(1_000_000, dtype=np.float64)   # 8 MB
        return [ray_tpu.put(arr)]

    [ref] = ray_tpu.get(produce.options(
        scheduling_strategy=_affinity(nb)).remote(), timeout=60)
    with rt._obj_cv:
        assert rt._obj_locations.pop(ref.id, None) is not None
    out = ray_tpu.get(ref, timeout=60)
    assert float(out[999_999]) == 999_999.0


def test_owner_map_updates_on_node_death(two_nodes):
    cluster, na, nb = two_nodes
    rt = ray_tpu.core.api.get_runtime()
    tag_b = owner_tag_of(nb.node_id)
    assert rt._owner_tags.get(tag_b) == nb.node_id
    rows = rt._node_map_rows()
    assert any(r[0] == nb.node_id for r in rows)
    cluster.remove_node(nb)
    deadline = time.time() + 15
    while (any(r[0] == nb.node_id for r in rt._node_map_rows())
           and time.time() < deadline):
        time.sleep(0.1)
    assert not any(r[0] == nb.node_id for r in rt._node_map_rows())
    # Owned route for a dead owner returns None -> fallback paths.
    oid = ObjectID.for_owned_put(tag_b)
    assert rt._owned_route(oid) is None
