"""Classic-tune compatibility surface (reference: tune.run family)."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import report


def test_samplers_shapes():
    import random
    from ray_tpu.tune.search import _sample
    r = random.Random(0)
    assert _sample(tune.quniform(0, 1, 0.25), r) in (
        0.0, 0.25, 0.5, 0.75, 1.0)
    assert isinstance(_sample(tune.qrandint(0, 100, 10), r), int)
    v = _sample(tune.lograndint(1, 1000), r)
    assert isinstance(v, int) and 1 <= v <= 1000
    assert isinstance(_sample(tune.randn(0, 1), r), float)
    got = _sample(tune.sample_from(lambda spec: spec.config["a"] * 2),
                  r, {"a": 21})
    assert got == 42


def test_run_with_parameters_and_dict_stop(rt):
    big = list(range(20_000))

    def obj(config, table):
        assert len(table) == 20_000
        for i in range(10):
            report({"loss": config["x"], "score": i})

    grid = tune.run(tune.with_parameters(obj, table=big),
                    config={"x": tune.grid_search([0.1, 0.2])},
                    metric="loss", mode="min", stop={"score": 4})
    # dict stop: each trial dies at its 5th report (score >= 4)
    assert all(len(t.metrics_history) <= 5 for t in grid)
    assert len(list(grid)) == 2


def test_register_trainable_and_stoppers(rt):
    tune.register_trainable(
        "compat_obj", lambda cfg: [report({"loss": 1.0})
                                   for _ in range(10)])
    grid = tune.run("compat_obj", config={},
                    stop=tune.MaximumIterationStopper(3))
    assert all(len(t.metrics_history) <= 3 for t in grid)
    with pytest.raises(ValueError, match="register_trainable"):
        tune.run("never_registered", config={})
    with pytest.raises(TypeError, match="unsupported arguments"):
        tune.run("compat_obj", config={}, fancy_new_arg=1)


def test_plateau_stopper():
    st = tune.TrialPlateauStopper(metric="m", std=0.01,
                                  num_results=3, grace_period=3)
    # improving metric: never stops
    assert not any(st("t", {"m": float(i)}) for i in range(6))
    # flat metric: stops once the window fills
    st2 = tune.TrialPlateauStopper(metric="m", std=0.01,
                                   num_results=3, grace_period=3)
    hits = [st2("t", {"m": 1.0}) for _ in range(4)]
    assert hits[-1] is True
