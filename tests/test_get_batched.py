"""Vectorized object plane: batched get/wait, pipelined chunk
transfers, and the deserialization cache.

Pins the semantics the vectorized paths must preserve from the old
serial loops — ordering, first-error-wins, partial timeout — plus the
new behaviors: cache hit/invalidate-on-delete and window-independent
chunk reassembly.
"""

import gc
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.exceptions import GetTimeoutError, TaskError
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.runtime import TransferPlane
from ray_tpu.core import serialization as ser
from ray_tpu.core.serialization import SerializedObject


# ---------------------------------------------------------------- get


def test_list_get_preserves_submit_order(rt):
    @ray_tpu.remote(num_cpus=1)
    def delayed(i, s):
        time.sleep(s)
        return i

    # Later-submitted tasks finish first; get() must return in list
    # order regardless.
    refs = [delayed.remote(i, 0.4 - 0.1 * i) for i in range(4)]
    assert ray_tpu.get(refs, timeout=60) == [0, 1, 2, 3]


def test_list_get_first_error_wins(rt):
    @ray_tpu.remote(num_cpus=1)
    def ok(i):
        return i

    @ray_tpu.remote(num_cpus=1)
    def boom(tag):
        raise ValueError(tag)

    r_ok = ok.remote(1)
    r_e1 = boom.remote("first-error")
    r_e2 = boom.remote("second-error")
    ray_tpu.wait([r_ok, r_e1, r_e2], num_returns=3, timeout=60)
    with pytest.raises(TaskError) as exc:
        ray_tpu.get([r_ok, r_e1, r_e2], timeout=60)
    assert "first-error" in str(exc.value)


def test_list_get_partial_timeout(rt):
    @ray_tpu.remote(num_cpus=1)
    def fast():
        return "fast"

    @ray_tpu.remote(num_cpus=1)
    def slow():
        time.sleep(30)
        return "slow"

    r_fast = fast.remote()
    r_slow = slow.remote()
    ray_tpu.wait([r_fast], num_returns=1, timeout=60)
    with pytest.raises(GetTimeoutError):
        ray_tpu.get([r_fast, r_slow], timeout=0.5)
    # wait() reports the partial set instead of raising.
    done, rest = ray_tpu.wait([r_fast, r_slow], num_returns=2,
                              timeout=0.5)
    assert len(done) == 1 and len(rest) == 1
    ray_tpu.cancel(r_slow, force=True)


def test_list_get_duplicate_refs(rt):
    ref = ray_tpu.put(b"dup")
    other = ray_tpu.put(b"other")
    assert ray_tpu.get([ref, other, ref], timeout=30) == \
        [b"dup", b"other", b"dup"]


def test_worker_batched_get_order_and_errors(rt):
    """The OP_GET_MANY path (worker-side list get) keeps order and
    error semantics of the per-ref loop."""
    refs = [ray_tpu.put(b"w%d" % i) for i in range(20)]

    @ray_tpu.remote(num_cpus=1)
    def get_all(ref_lists):
        return ray_tpu.get(ref_lists[0])

    assert ray_tpu.get(get_all.remote([refs]), timeout=120) == \
        [b"w%d" % i for i in range(20)]

    @ray_tpu.remote(num_cpus=1)
    def boom():
        raise ValueError("inner-error")

    bad = boom.remote()
    ray_tpu.wait([bad], num_returns=1, timeout=60)
    with pytest.raises(TaskError) as exc:
        ray_tpu.get(get_all.remote([[refs[0], bad]]), timeout=120)
    assert "inner-error" in str(exc.value)


# ------------------------------------------------- deserialization cache


def test_deser_cache_hit_and_identity(rt):
    runtime = ray_tpu.core.api.get_runtime()
    ref = ray_tpu.put(np.arange(1 << 20, dtype=np.uint8))  # 1 MiB
    v1 = ray_tpu.get(ref, timeout=30)
    hits0 = runtime.deser_cache_hits
    v2 = ray_tpu.get(ref, timeout=30)
    assert runtime.deser_cache_hits == hits0 + 1
    assert v2 is v1                      # cached value, no re-deser
    assert not v1.flags.writeable        # shared pages stay immutable


def test_deser_cache_invalidated_on_delete(rt):
    runtime = ray_tpu.core.api.get_runtime()
    ref = ray_tpu.put(np.zeros(1 << 20, dtype=np.uint8))
    ray_tpu.get(ref, timeout=30)
    oid = ref.id
    assert oid in runtime._deser_cache
    del ref
    gc.collect()
    assert oid not in runtime._deser_cache


def test_deser_cache_skips_small_objects(rt):
    runtime = ray_tpu.core.api.get_runtime()
    ref = ray_tpu.put(b"tiny")           # far below deser_cache_min
    ray_tpu.get(ref, timeout=30)
    assert ref.id not in runtime._deser_cache
    # And repeated gets of uncached values return fresh copies.
    a = ray_tpu.get(ref, timeout=30)
    b = ray_tpu.get(ref, timeout=30)
    assert a == b == b"tiny"


def test_deser_cache_lru_byte_budget():
    from ray_tpu.core.deser_cache import DeserializationCache
    cache = DeserializationCache(max_bytes=100, min_bytes=10)
    cache.offer("a", "A", 40)
    cache.offer("b", "B", 40)
    assert cache.lookup("a") == (True, "A")
    cache.offer("c", "C", 40)            # evicts LRU ("b")
    assert cache.lookup("b") == (False, None)
    assert cache.lookup("a") == (True, "A")
    assert not cache.offer("tiny", "t", 5)     # below min
    assert not cache.offer("huge", "h", 500)   # above budget
    cache.invalidate("a")
    assert cache.lookup("a") == (False, None)
    assert cache.hits == 2 and cache.misses == 2


# --------------------------------------------- pipelined chunk transfers


def _chunk_roundtrip(window: int, chunk_bytes: int = 1024) -> bytes:
    payload = bytes(range(256)) * 37          # multi-chunk, odd tail
    obj = SerializedObject(data=payload[:100],
                           buffers=[payload[100:], b"tail"])
    plane = TransferPlane(chunk_bytes)
    meta = plane.start(obj)
    out = ser.reassemble_chunked(meta, plane.chunk, plane.end,
                                 window=window)
    assert not plane.table                    # transfer ended
    assert out.data == obj.data
    assert [bytes(b) for b in out.buffers] == \
        [bytes(b) for b in obj.buffers]
    return bytes(out.data)


def test_reassemble_chunked_window_equivalence():
    assert _chunk_roundtrip(window=1) == _chunk_roundtrip(window=8)


def test_reassemble_chunked_window_error_propagates():
    plane = TransferPlane(256)
    obj = SerializedObject(data=b"d" * 2048, buffers=[])
    meta = plane.start(obj)

    calls = []

    def flaky(tid, i):
        calls.append(i)
        if i == 3:
            raise RuntimeError("chunk 3 lost")
        return plane.chunk(tid, i)

    with pytest.raises(RuntimeError, match="chunk 3 lost"):
        ser.reassemble_chunked(meta, flaky, plane.end, window=4)
    assert not plane.table                    # end ran despite error


def test_reassemble_chunked_stream_pipelines():
    """The in-order stream variant: equivalence with the serial path
    plus the send-ahead window actually keeping requests in flight."""
    plane = TransferPlane(512)
    payload = bytes(range(256)) * 23
    obj = SerializedObject(data=payload, buffers=[payload[::-1]])
    meta = plane.start(obj)

    inflight = []
    max_inflight = [0]
    reqs = []

    def send_req(tid, i):
        reqs.append(i)
        inflight.append(i)
        max_inflight[0] = max(max_inflight[0], len(inflight))

    def recv_piece():
        i = inflight.pop(0)
        return plane.chunk(meta[1], i)

    out = ser.reassemble_chunked_stream(
        meta, send_req, recv_piece,
        lambda tid: plane.end(tid), window=4)
    assert out.data == obj.data
    assert bytes(out.buffers[0]) == payload[::-1]
    assert reqs == sorted(reqs)               # in-order requests
    assert max_inflight[0] == 4               # window saturated
    assert not plane.table


@pytest.mark.slow
def test_chunked_get_window_equivalence_end_to_end(rt):
    """A no-shm worker pulls a >inline-max object through the chunk
    plane with window=1 and window=8; payloads must be identical."""
    big = ray_tpu.put(np.arange(12 << 20, dtype=np.uint8))

    @ray_tpu.remote(num_cpus=1)
    def pull(ref_list):
        v = ray_tpu.get(ref_list[0])
        return int(v[:1000].sum()), v.nbytes

    outs = []
    for window in ("1", "8"):
        env = {"env_vars": {"RAY_TPU_NO_SHM": "1",
                            "RAY_TPU_OBJECT_TRANSFER_WINDOW": window}}
        outs.append(ray_tpu.get(
            pull.options(runtime_env=env).remote([big]), timeout=180))
    assert outs[0] == outs[1]


def test_get_many_reply_frame_budget(rt):
    """A fan-in of large inline objects splits across reply frames:
    the server defers entries past object_transfer_inline_max per
    round and the client re-requests them — payloads must come back
    complete and ordered, in more than one wire round but far fewer
    than one per ref."""
    n, mib = 6, 3                      # 18 MiB total, 8 MiB budget
    refs = [ray_tpu.put(np.full(mib << 20, i, dtype=np.uint8))
            for i in range(n)]

    @ray_tpu.remote(num_cpus=1)
    def pull(ref_lists):
        from ray_tpu.core.api import get_runtime
        runtime = get_runtime()
        before = runtime.wire_rounds
        vals = ray_tpu.get(ref_lists[0])
        rounds = runtime.wire_rounds - before
        return rounds, [int(v[0]) for v in vals], \
            [v.nbytes for v in vals]

    env = {"env_vars": {"RAY_TPU_NO_SHM": "1",
                        "RAY_TPU_DESER_CACHE_MAX_BYTES": "0"}}
    rounds, firsts, sizes = ray_tpu.get(
        pull.options(runtime_env=env).remote([refs]), timeout=180)
    assert firsts == list(range(n))
    assert sizes == [mib << 20] * n
    assert 2 <= rounds <= n            # split, but not per-ref


# ----------------------------------------------------- batched wait


def test_wait_then_get_consistency(rt):
    @ray_tpu.remote(num_cpus=1)
    def val(i):
        return i * 10

    refs = [val.remote(i) for i in range(8)]
    done, rest = ray_tpu.wait(refs, num_returns=8, timeout=60)
    assert not rest
    # wait's availability probe and get's batched resolve agree.
    assert ray_tpu.get(done, timeout=30) == \
        [r * 10 for r in range(8)]
