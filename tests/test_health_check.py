"""Active health checking of node daemons (reference:
GcsHealthCheckManager, gcs_health_check_manager.h:39; threshold flags
ray_config_def.h:847). EOF-only detection misses a wedged-but-connected
daemon — SIGSTOP one and the head must declare it dead within the
configured period*threshold and fail its work over; SIGCONT lets it
re-register."""

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
)


@pytest.fixture
def fast_health_env():
    # Scoped config injection (VERDICT r3 item 8): env + cached
    # config swapped atomically, restored on exit — no private-global
    # poking.
    from ray_tpu.core.config import env_overrides
    with env_overrides(health_check_period_s=0.2,
                       health_check_failure_threshold=5):
        yield


def test_sigstop_daemon_is_declared_dead_and_failed_over(
        fast_health_env):
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    try:
        nb = cluster.add_node(num_cpus=1)
        rt = ray_tpu.core.api.get_runtime()

        @ray_tpu.remote(num_cpus=1, max_retries=2)
        def work():
            return ray_tpu.get_runtime_context().get_node_id()

        # Warm: nb runs tasks.
        out = ray_tpu.get(work.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                nb.node_id, soft=True)).remote(), timeout=60)
        assert out == nb.node_id

        # Wedge the daemon WITHOUT killing it: TCP stays open, so
        # only an active health check can notice.
        os.kill(nb.proc.pid, signal.SIGSTOP)
        try:
            deadline = time.time() + 15
            while (rt._nodes[nb.node_id].alive
                   and time.time() < deadline):
                time.sleep(0.1)
            took = 15 - (deadline - time.time())
            assert not rt._nodes[nb.node_id].alive, \
                "wedged daemon never declared dead"
            # period 0.2 * threshold 5 = 1s nominal; allow slack.
            assert took < 10, took

            # Work keeps flowing on the remaining node (the task that
            # preferred nb re-homes).
            out = ray_tpu.get(work.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    nb.node_id, soft=True)).remote(), timeout=60)
            assert out != nb.node_id
        finally:
            os.kill(nb.proc.pid, signal.SIGCONT)

        # The un-wedged daemon reconnects and revives.
        deadline = time.time() + 30
        while (not rt._nodes[nb.node_id].alive
               and time.time() < deadline):
            time.sleep(0.2)
        assert rt._nodes[nb.node_id].alive, "daemon never re-registered"
    finally:
        cluster.shutdown()


def test_healthy_daemons_stay_alive(fast_health_env):
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    try:
        nb = cluster.add_node(num_cpus=1)
        rt = ray_tpu.core.api.get_runtime()
        # Several threshold windows pass with no false positives.
        time.sleep(3.0)
        assert rt._nodes[nb.node_id].alive

        @ray_tpu.remote(num_cpus=1)
        def sq(x):
            return x * x

        assert ray_tpu.get(sq.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                nb.node_id)).remote(7), timeout=60) == 49
    finally:
        cluster.shutdown()
