"""JaxTrainer end-to-end tests (reference analog: train e2e suite).

Worker actors are separate processes with 8 virtual CPU devices each
(XLA_FLAGS is inherited), mirroring the reference's
multi-node-on-one-machine test pattern.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint, JaxTrainer, RunConfig, FailureConfig, ScalingConfig,
    get_context, report,
)


def _loop_gpt_tiny(config):
    import jax
    import optax

    from ray_tpu.models import GPT2, GPT2Config
    from ray_tpu.models.gpt2 import gpt2_loss_fn
    from ray_tpu.parallel import make_mesh
    from ray_tpu.train import (
        init_train_state, make_train_step, shard_batch, report,
    )

    mesh = make_mesh({"dp": -1})
    cfg = GPT2Config.tiny()
    model = GPT2(cfg, mesh=mesh)
    params = model.init_params(jax.random.key(0))
    opt = optax.adamw(1e-2)
    state = init_train_state(params, opt, mesh)
    step = make_train_step(gpt2_loss_fn(model), opt)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          (8, cfg.seq_len)).astype(np.int32)
    batch = shard_batch(
        {"tokens": tokens, "targets": np.roll(tokens, -1, 1)}, mesh)
    for i in range(config.get("steps", 3)):
        state, metrics = step(state, batch)
        report({"loss": float(metrics["loss"]), "step": i})


def test_trainer_single_worker(rt):
    trainer = JaxTrainer(
        _loop_gpt_tiny,
        train_loop_config={"steps": 4},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path="/tmp/ray_tpu_test_exp"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 3
    assert len(result.metrics_history) == 4
    assert np.isfinite(result.metrics["loss"])


def _loop_with_checkpoint(config):
    import json
    import tempfile

    from ray_tpu.train import Checkpoint, get_context, report

    ctx = get_context()
    start = 0
    if ctx.restored_checkpoint_dir:
        with open(os.path.join(ctx.restored_checkpoint_dir,
                               "state.json")) as f:
            start = json.load(f)["step"] + 1
    for i in range(start, config["steps"]):
        d = tempfile.mkdtemp()
        with open(os.path.join(d, "state.json"), "w") as f:
            json.dump({"step": i}, f)
        if config.get("crash_at") == i and not ctx.restored_checkpoint_dir:
            os._exit(1)
        report({"step": i}, checkpoint=Checkpoint.from_directory(d))


def test_trainer_checkpoint_and_restore_after_failure(rt):
    trainer = JaxTrainer(
        _loop_with_checkpoint,
        train_loop_config={"steps": 5, "crash_at": 3},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path="/tmp/ray_tpu_test_exp",
            failure_config=FailureConfig(max_failures=2)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    # completed through step 4 after restart from step-2 checkpoint
    assert result.metrics["step"] == 4
    assert result.checkpoint_dir is not None
    assert os.path.exists(result.checkpoint_dir)


def test_trainer_user_error_no_retry(rt):
    def bad_loop(config):
        raise ValueError("training exploded")

    trainer = JaxTrainer(
        bad_loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path="/tmp/ray_tpu_test_exp"),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "training exploded" in result.error


def _loop_rank_report(config):
    from ray_tpu.train import get_context, report
    ctx = get_context()
    report({"rank": ctx.world_rank, "world": ctx.world_size})


def test_trainer_two_workers_context(rt):
    trainer = JaxTrainer(
        _loop_rank_report,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path="/tmp/ray_tpu_test_exp"),
    )
    # Two workers needs jax.distributed across processes; our loop
    # doesn't use collectives, but rendezvous must succeed.
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["world"] == 2
    assert result.metrics["rank"] == 0


def test_checkpoint_pytree_roundtrip(tmp_path):
    import jax.numpy as jnp

    from ray_tpu.train.checkpoint import restore_pytree, save_pytree

    tree = {"w": jnp.arange(8.0), "b": {"x": jnp.ones((2, 2))}}
    save_pytree(tree, str(tmp_path))
    out = restore_pytree(str(tmp_path))
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.arange(8.0))
    np.testing.assert_allclose(np.asarray(out["b"]["x"]),
                               np.ones((2, 2)))


def test_latest_complete_checkpoint_prefers_disk(tmp_path):
    """Recovery must trust on-disk completed checkpoints over the
    polled stream: a worker can persist + die before the driver polls
    the matching report."""
    from ray_tpu.train.trainer import _latest_complete_checkpoint

    trial = str(tmp_path)
    for idx, complete in [(0, True), (1, True), (2, False)]:
        d = os.path.join(trial, f"checkpoint_{idx:06d}")
        os.makedirs(d)
        if complete:
            open(os.path.join(d, ".complete_rank_0"), "w").close()

    # Driver polled nothing: picks newest *complete* dir (idx 1).
    got = _latest_complete_checkpoint(trial, None)
    assert got is not None and got.endswith("checkpoint_000001")
    # Polled state older than disk: disk wins.
    got = _latest_complete_checkpoint(
        trial, os.path.join(trial, "checkpoint_000000"))
    assert got is not None and got.endswith("checkpoint_000001")
    # Polled state newer than any completed dir: polled wins.
    newer = os.path.join(trial, "checkpoint_000009")
    assert _latest_complete_checkpoint(trial, newer) == newer


def test_session_index_monotonic_after_restore():
    from ray_tpu.train.session import checkpoint_index

    assert checkpoint_index(None) == -1
    assert checkpoint_index("/a/b/checkpoint_000004") == 4
    assert checkpoint_index("/a/b/weird") == -1


def test_trainer_datasets_shard_to_workers(rt, tmp_path):
    """datasets={...} (reference: DataParallelTrainer datasets= +
    get_dataset_shard): streaming_split per worker, disjoint shards
    covering every row exactly once; get_checkpoint() is None on a
    fresh run."""
    import json
    import os

    import ray_tpu
    from ray_tpu import data
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    out_dir = str(tmp_path / "shards")
    os.makedirs(out_dir, exist_ok=True)

    def loop():
        from ray_tpu.train import (
            get_checkpoint, get_context, get_dataset_shard, report,
        )
        assert get_checkpoint() is None
        ctx = get_context()
        ids = []
        for b in get_dataset_shard("train").iter_batches(
                batch_size=16):
            ids.extend(int(x) for x in b["id"])
        with open(os.path.join(
                os.environ["SHARD_OUT"],
                f"rank{ctx.world_rank}.json"), "w") as f:
            json.dump(ids, f)
        report({"n": len(ids)})

    os.environ["SHARD_OUT"] = out_dir
    try:
        tr = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(storage_path=str(tmp_path)),
            datasets={"train": data.range(100)})
        res = tr.fit()
        assert res.error is None, res.error
    finally:
        os.environ.pop("SHARD_OUT", None)
    shards = []
    for r in (0, 1):
        with open(os.path.join(out_dir, f"rank{r}.json")) as f:
            shards.append(json.load(f))
    all_ids = sorted(shards[0] + shards[1])
    assert all_ids == list(range(100))           # full coverage
    assert not set(shards[0]) & set(shards[1])   # disjoint
    assert shards[0] and shards[1]               # both worked


def test_data_config_replicates_unsplit_datasets(rt, tmp_path):
    """DataConfig(datasets_to_split=[...]) (reference:
    ray.train.DataConfig): listed datasets shard across workers,
    unlisted ones replicate — every worker sees the FULL stream."""
    import json
    import os

    from ray_tpu import data
    from ray_tpu.train import (
        DataConfig, JaxTrainer, RunConfig, ScalingConfig,
    )

    out_dir = str(tmp_path / "repl")
    os.makedirs(out_dir, exist_ok=True)

    def loop():
        from ray_tpu.train import get_context, get_dataset_shard, report
        ctx = get_context()
        train_ids = [int(x)
                     for b in get_dataset_shard("train").iter_batches(
                         batch_size=16) for x in b["id"]]
        val_ids = [int(x)
                   for b in get_dataset_shard("val").iter_batches(
                       batch_size=16) for x in b["id"]]
        with open(os.path.join(os.environ["REPL_OUT"],
                               f"rank{ctx.world_rank}.json"),
                  "w") as f:
            json.dump({"train": train_ids, "val": val_ids}, f)
        report({"n": len(train_ids)})

    os.environ["REPL_OUT"] = out_dir
    try:
        tr = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(storage_path=str(tmp_path)),
            datasets={"train": data.range(40),
                      "val": data.range(10)},
            dataset_config=DataConfig(datasets_to_split=["train"]))
        res = tr.fit()
        assert res.error is None, res.error
    finally:
        os.environ.pop("REPL_OUT", None)
    shards = []
    for r in (0, 1):
        with open(os.path.join(out_dir, f"rank{r}.json")) as f:
            shards.append(json.load(f))
    # train: disjoint full coverage; val: FULL copy on every worker
    assert sorted(shards[0]["train"] + shards[1]["train"]) == \
        list(range(40))
    assert not set(shards[0]["train"]) & set(shards[1]["train"])
    assert sorted(shards[0]["val"]) == list(range(10))
    assert sorted(shards[1]["val"]) == list(range(10))
