"""Kubernetes node provider (reference:
python/ray/autoscaler/_private/kuberay/node_provider.py — pods scaled
through the API server; tested here against a fake API transport, the
same zero-egress pattern as gce_tpu's MockRunner)."""

import json

import pytest

from ray_tpu.autoscaler.k8s import K8sConfig, K8sNodeProvider


class FakeApiServer:
    """Injectable transport: a dict of pods + a request log."""

    def __init__(self):
        self.pods: dict[str, dict] = {}
        self.log: list[tuple[str, str]] = []

    def request(self, method, path, body=None):
        self.log.append((method, path))
        if method == "POST" and path.endswith("/pods"):
            name = body["metadata"]["name"]
            if name in self.pods:
                return 409, {"reason": "AlreadyExists"}
            self.pods[name] = body
            return 201, body
        if method == "DELETE":
            name = path.rsplit("/", 1)[-1]
            return (200, {}) if self.pods.pop(name, None) \
                else (404, {})
        if method == "GET" and "/pods" in path:
            return 200, {"items": [
                {"metadata": p["metadata"],
                 "status": {"phase": "Running"}}
                for p in self.pods.values()]}
        return 404, {}


def _provider(**cfg):
    api = FakeApiServer()
    defaults = dict(namespace="ns", name_prefix="raytpu",
                    head_address="10.0.0.2:6380",
                    cluster_token="deadbeef",
                    accelerator_types={"v5e_8": "v5e-8"},
                    tpu_chips={"v5e_8": 8})
    defaults.update(cfg)
    return K8sNodeProvider(K8sConfig(**defaults), transport=api), api


def test_create_node_posts_tpu_pod():
    p, api = _provider()
    nid = p.create_node("v5e_8", {"CPU": 8, "TPU": 8})
    assert nid in api.pods
    pod = api.pods[nid]
    assert pod["metadata"]["namespace"] == "ns"
    assert pod["metadata"]["labels"]["ray-tpu.io/cluster"] == "raytpu"
    spec = pod["spec"]
    c = spec["containers"][0]
    # Device-plugin chips + GKE TPU node selector + gang resource in
    # the daemon command + head address + token env.
    assert c["resources"]["limits"]["google.com/tpu"] == 8
    assert spec["nodeSelector"][
        "cloud.google.com/gke-tpu-accelerator"] == "v5e-8"
    cmd = c["command"][-1]
    assert "TPU-v5e-8-head" in cmd
    assert "--address 10.0.0.2:6380" in cmd
    assert c["env"][0]["value"] == "deadbeef"
    assert len(p.non_terminated_nodes()) == 1


def test_cpu_node_type_has_no_tpu_bits():
    p, api = _provider()
    nid = p.create_node("cpu", {"CPU": 4})
    pod = api.pods[nid]
    assert "nodeSelector" not in pod["spec"]
    assert "resources" not in pod["spec"]["containers"][0]
    assert "TPU-" not in pod["spec"]["containers"][0]["command"][-1]


def test_terminate_deletes_pod():
    p, api = _provider()
    nid = p.create_node("v5e_8", {"CPU": 8})
    p.terminate_node(nid)
    assert api.pods == {}
    assert p.non_terminated_nodes() == []
    # Deleting an already-gone pod (404) is not an error.
    p.terminate_node(nid)


def test_refresh_adopts_and_drops_pods():
    p, api = _provider()
    api.pods["raytpu-v5e_8-zzz"] = {
        "metadata": {"name": "raytpu-v5e_8-zzz", "namespace": "ns",
                     "labels": {"ray-tpu.io/cluster": "raytpu",
                                "ray-tpu.io/node-type": "v5e_8"}},
        "spec": {}}
    p.refresh()
    nodes = p.non_terminated_nodes()
    assert [n.node_id for n in nodes] == ["raytpu-v5e_8-zzz"]
    assert nodes[0].node_type == "v5e_8"
    api.pods.clear()
    p.refresh()
    assert p.non_terminated_nodes() == []


def test_pod_spec_overrides_merge():
    p, api = _provider(pod_spec_overrides={
        "serviceAccountName": "ray-sa",
        "nodeSelector": {"pool": "tpu-pool"}})
    nid = p.create_node("v5e_8", {"CPU": 8})
    spec = api.pods[nid]["spec"]
    assert spec["serviceAccountName"] == "ray-sa"
    # Dict overrides merge with generated keys instead of replacing.
    assert spec["nodeSelector"]["pool"] == "tpu-pool"
    assert spec["nodeSelector"][
        "cloud.google.com/gke-tpu-accelerator"] == "v5e-8"


def test_create_failure_surfaces():
    p, api = _provider()
    nid = p.create_node("v5e_8", {"CPU": 8})
    # Duplicate name -> 409 -> error (no silent half-created node).
    api.pods["raytpu-v5e_8-dup"] = {}

    class Dup:
        def request(self, method, path, body=None):
            return 409, {"reason": "AlreadyExists"}

    p2 = K8sNodeProvider(K8sConfig(namespace="ns"), transport=Dup())
    with pytest.raises(RuntimeError):
        p2.create_node("cpu", {})
    assert nid  # first provider unaffected


def test_launcher_builds_k8s_provider(tmp_path):
    """launcher YAML with provider: k8s creates/terminates fake pods
    (VERDICT r3 item 10 done-condition)."""
    from ray_tpu.autoscaler.launcher import _build_provider

    api = FakeApiServer()
    cfg = {
        "cluster_name": "t",
        "provider": {"type": "k8s", "namespace": "prod",
                     "head_address": "1.2.3.4:6380",
                     "_transport": api},
        "node_types": {
            "v5e_8": {"resources": {"CPU": 8, "TPU": 8},
                      "accelerator_type": "v5e-8", "tpu_chips": 8},
        },
    }
    p = _build_provider(cfg, runtime=None)
    nid = p.create_node("v5e_8", {"CPU": 8, "TPU": 8})
    assert api.pods[nid]["metadata"]["namespace"] == "prod"
    assert api.pods[nid]["spec"]["containers"][0]["resources"][
        "limits"]["google.com/tpu"] == 8
    p.terminate_node(nid)
    assert api.pods == {}
