"""Mutable-object channels + channel-mode compiled DAGs (reference
analog: python/ray/tests/test_channel.py and
test_accelerated_dag.py over mutable plasma objects /
shared_memory_channel.py)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.native.channel import (
    Channel,
    ChannelClosedError,
    ChannelTimeoutError,
    channels_available,
)

pytestmark = pytest.mark.skipif(
    not channels_available(), reason="native channel lib unavailable")


# -- raw channel primitive ----------------------------------------------


def test_channel_same_process_roundtrip():
    ch = Channel(1 << 20)
    ch.register_reader()
    ch.write({"x": 1})
    assert ch.read(timeout=5) == {"x": 1}
    arr = np.arange(1000, dtype=np.float32)
    ch.write(arr)
    np.testing.assert_array_equal(ch.read(timeout=5), arr)
    ch.close()
    with pytest.raises(ChannelClosedError):
        ch.read(timeout=5)
    ch.detach()


def test_channel_depth_one_backpressure():
    ch = Channel(1 << 16)
    ch.register_reader()
    ch.write(1)
    with pytest.raises(ChannelTimeoutError):
        ch.write(2, timeout=0.2)       # reader hasn't consumed v1
    assert ch.read(timeout=5) == 1
    ch.write(2, timeout=5)             # now it fits
    assert ch.read(timeout=5) == 2
    ch.detach()


def test_channel_oversize_value_rejected():
    ch = Channel(1024)
    with pytest.raises(ValueError, match="exceeds channel buffer"):
        ch.write(np.zeros(100_000))
    ch.detach()


def test_channel_zero_copy_read_view():
    ch = Channel(1 << 20)
    ch.register_reader()
    src = np.arange(256, dtype=np.int64)
    ch.write(src)
    value, is_err = ch.begin_read(timeout=5)
    assert not is_err
    np.testing.assert_array_equal(value, src)
    ch.end_read()
    ch.detach()


def test_channel_cross_process(rt):
    @ray_tpu.remote
    class Consumer:
        def consume(self, name, n):
            c = Channel(0, name)
            c.register_reader()
            total = 0.0
            for _ in range(n):
                total += float(c.read(timeout=10))
            return total

    ch = Channel(1 << 20)
    a = Consumer.remote()
    fut = a.consume.remote(ch.name, 5)
    deadline = time.time() + 10
    while ch.reader_count() < 1:
        assert time.time() < deadline
        time.sleep(0.005)
    for i in range(5):
        ch.write(float(i), timeout=10)
    assert ray_tpu.get(fut) == 10.0
    ch.close()
    ch.detach()


# -- channel-mode compiled DAGs -----------------------------------------


def test_channel_dag_mode_selected(rt):
    @ray_tpu.remote
    class A:
        def f(self, x):
            return x * 2

    with InputNode() as inp:
        dag = A.bind().f.bind(inp)
    cdag = dag.experimental_compile()
    try:
        assert cdag._mode == "channels"
        assert ray_tpu.get(cdag.execute(21)) == 42
    finally:
        cdag.teardown()


def test_channel_dag_function_nodes_fall_back(rt):
    @ray_tpu.remote
    def f(x):
        return x + 1

    with InputNode() as inp:
        dag = f.bind(inp)
    cdag = dag.experimental_compile()
    try:
        assert cdag._mode == "tasks"
        assert ray_tpu.get(cdag.execute(1)) == 2
    finally:
        cdag.teardown()


def test_channel_dag_cross_actor_diamond(rt):
    @ray_tpu.remote
    class Node:
        def __init__(self, k):
            self.k = k

        def apply(self, *xs):
            return sum(xs) + self.k

    with InputNode() as inp:
        src = Node.bind(1).apply.bind(inp)        # x + 1
        left = Node.bind(0).apply.bind(src)       # x + 1
        right = Node.bind(100).apply.bind(src)    # x + 101
        dag = Node.bind(0).apply.bind(left, right)  # 2x + 102

    cdag = dag.experimental_compile()
    try:
        assert cdag._mode == "channels"
        assert ray_tpu.get(cdag.execute(0)) == 102
        assert ray_tpu.get(cdag.execute(5)) == 112
    finally:
        cdag.teardown()


def test_channel_dag_multi_output(rt):
    @ray_tpu.remote
    class W:
        def __init__(self, k):
            self.k = k

        def mul(self, x):
            return x * self.k

    with InputNode() as inp:
        dag = MultiOutputNode([W.bind(2).mul.bind(inp),
                               W.bind(3).mul.bind(inp), inp])

    cdag = dag.experimental_compile()
    try:
        assert cdag._mode == "channels"
        assert ray_tpu.get(cdag.execute(5)) == [10, 15, 5]
    finally:
        cdag.teardown()


def test_channel_dag_error_propagates_and_recovers(rt):
    @ray_tpu.remote
    class S:
        def step(self, x):
            if x < 0:
                raise ValueError("negative input")
            return x + 1

    with InputNode() as inp:
        s1 = S.bind()
        s2 = S.bind()
        dag = s2.step.bind(s1.step.bind(inp))

    cdag = dag.experimental_compile()
    try:
        assert cdag._mode == "channels"
        assert ray_tpu.get(cdag.execute(1)) == 3
        with pytest.raises(Exception, match="negative input"):
            ray_tpu.get(cdag.execute(-5))
        # The pipeline stays aligned after an error.
        assert ray_tpu.get(cdag.execute(10)) == 12
    finally:
        cdag.teardown()


def test_channel_dag_numpy_payload(rt):
    @ray_tpu.remote
    class M:
        def scale(self, x):
            return x * 2.0

    with InputNode() as inp:
        dag = M.bind().scale.bind(inp)
    cdag = dag.experimental_compile()
    try:
        x = np.random.default_rng(0).normal(size=(64, 64))
        out = ray_tpu.get(cdag.execute(x))
        np.testing.assert_allclose(out, x * 2.0)
    finally:
        cdag.teardown()


def test_channel_dag_sustained_pipeline_throughput(rt):
    @ray_tpu.remote
    class P:
        def f(self, x):
            return x + 1

    with InputNode() as inp:
        s1, s2 = P.bind(), P.bind()
        dag = s2.f.bind(s1.f.bind(inp))

    cdag = dag.experimental_compile()
    try:
        assert cdag._mode == "channels"
        ray_tpu.get(cdag.execute(0))   # warm both loops
        n = 200
        t0 = time.perf_counter()
        refs = [cdag.execute(i) for i in range(n)]
        out = [r.get(timeout=30) for r in refs]
        dt = time.perf_counter() - t0
        assert out == [i + 2 for i in range(n)]
        rate = n / dt
        # Shm-channel pipeline should sustain >200 exec/s; the RPC
        # path is an order of magnitude slower per stage round-trip.
        assert rate > 200, f"only {rate:.0f} executions/s"
    finally:
        cdag.teardown()


def test_channel_dag_teardown_unblocks_loops(rt):
    @ray_tpu.remote
    class Q:
        def f(self, x):
            return x

    with InputNode() as inp:
        dag = Q.bind().f.bind(inp)
    cdag = dag.experimental_compile()
    handle = cdag._owned_actors[0]
    assert ray_tpu.get(cdag.execute(1)) == 1
    cdag.teardown()
    deadline = time.time() + 30
    while handle.state() != "DEAD" and time.time() < deadline:
        time.sleep(0.1)
    assert handle.state() == "DEAD"
    with pytest.raises(RuntimeError, match="torn down"):
        cdag.execute(2)


def test_channel_dag_actor_feeds_and_consumes(rt):
    # a -> b -> a: actor a must write its first node before blocking
    # on b's output (per-node interleaved reads, not hoisted).
    @ray_tpu.remote
    class T:
        def f(self, x):
            return x + 1

    with InputNode() as inp:
        a = T.bind()
        b = T.bind()
        t1 = a.f.bind(inp)
        t2 = b.f.bind(t1)
        dag = a.f.bind(t2)

    cdag = dag.experimental_compile()
    try:
        assert cdag._mode == "channels"
        assert ray_tpu.get(cdag.execute(0)) == 3
        assert ray_tpu.get(cdag.execute(10)) == 13
    finally:
        cdag.teardown()


def test_channel_dag_oversized_result_ships_error(rt):
    @ray_tpu.remote
    class Big:
        def make(self, n):
            return np.zeros(n, dtype=np.float64)

    with InputNode() as inp:
        dag = Big.bind().make.bind(inp)
    cdag = dag.experimental_compile(buffer_size_bytes=1 << 16)
    try:
        assert cdag._mode == "channels"
        with pytest.raises(Exception, match="exceeds channel buffer"):
            ray_tpu.get(cdag.execute(1_000_000))
        # Loop survives; small results still flow.
        out = ray_tpu.get(cdag.execute(16))
        assert out.shape == (16,)
    finally:
        cdag.teardown()


def test_channel_dag_live_handle_falls_back_to_tasks(rt):
    @ray_tpu.remote
    class L:
        def f(self, x):
            return x * 3

    h = L.remote()
    with InputNode() as inp:
        dag = h.f.bind(inp)
    cdag = dag.experimental_compile()
    try:
        # Channel mode would hijack the user's actor loop; task-mode
        # fallback keeps ordinary .remote() calls working.
        assert cdag._mode == "tasks"
        assert ray_tpu.get(cdag.execute(2)) == 6
        assert ray_tpu.get(h.f.remote(1)) == 3   # actor still usable
    finally:
        cdag.teardown()
    ray_tpu.kill(h)


def test_channel_dag_get_timeout_is_retryable(rt):
    @ray_tpu.remote
    class Slow:
        def f(self, x):
            time.sleep(1.0)
            return x

    with InputNode() as inp:
        dag = Slow.bind().f.bind(inp)
    cdag = dag.experimental_compile()
    try:
        ref = cdag.execute(9)
        from ray_tpu.native.channel import ChannelTimeoutError
        with pytest.raises(ChannelTimeoutError):
            ref.get(timeout=0.05)
        assert ref.get(timeout=30) == 9   # timeout did not poison it
    finally:
        cdag.teardown()


def test_channel_dag_ref_get_twice_rejected(rt):
    @ray_tpu.remote
    class R:
        def f(self, x):
            return x

    with InputNode() as inp:
        dag = R.bind().f.bind(inp)
    cdag = dag.experimental_compile()
    try:
        ref = cdag.execute(7)
        assert ref.get(timeout=10) == 7
        with pytest.raises(ValueError, match="already retrieved"):
            ref.get()
    finally:
        cdag.teardown()
