"""Internal KV, head-state snapshot/recovery, chaos fault injection.

Reference analogs: GCS InternalKV (gcs_kv_manager.cc), GCS HA via
Redis-journaled tables + restart replay (SURVEY.md §5.3), and the
ResourceKiller test utils (§4.1(4)).
"""

import os
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu.experimental import internal_kv
from ray_tpu.util import ha
from ray_tpu.util.chaos import ResourceKiller


def test_internal_kv_basics(rt):
    assert internal_kv.kv_get("missing") is None
    internal_kv.kv_put("a", b"1")
    assert internal_kv.kv_get("a") == b"1"
    assert internal_kv.kv_exists("a")
    # no-overwrite honored
    assert internal_kv.kv_put("a", b"2", overwrite=False) is False
    assert internal_kv.kv_get("a") == b"1"
    # namespaces isolate
    internal_kv.kv_put("a", b"ns", namespace="other")
    assert internal_kv.kv_get("a", namespace="other") == b"ns"
    assert internal_kv.kv_get("a") == b"1"
    internal_kv.kv_put("ab", b"3")
    assert sorted(internal_kv.kv_list("a")) == [b"a", b"ab"]
    assert internal_kv.kv_del("a") is True
    assert not internal_kv.kv_exists("a")


@ray_tpu.remote
def kv_from_worker():
    from ray_tpu.experimental import internal_kv as kv
    kv.kv_put("from_worker", b"hello")
    return kv.kv_get("from_worker")


def test_internal_kv_from_worker(rt):
    assert ray_tpu.get(kv_from_worker.remote(), timeout=60) == b"hello"
    assert internal_kv.kv_get("from_worker") == b"hello"


@ray_tpu.remote
class NamedCounter:
    def __init__(self, start=0):
        self.n = start

    def incr(self):
        self.n += 1
        return self.n


def test_head_state_snapshot_and_restore():
    snap = os.path.join(tempfile.mkdtemp(), "head.json")
    ray_tpu.init(num_cpus=4)
    try:
        internal_kv.kv_put("cfg", b"v1")
        c = NamedCounter.options(name="counter").remote(10)
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 11
        pg = ray_tpu.placement_group([{"CPU": 1}], strategy="PACK")
        pg.ready(timeout=30)
        counts = ha.save_head_state(snap)
        assert counts["named_actors"] == 1 and counts["pgs"] == 1
    finally:
        ray_tpu.shutdown()   # the head "dies"

    ray_tpu.init(num_cpus=4)
    try:
        restored = ha.restore_head_state(snap)
        assert restored["named_actors"] == ["counter"]
        assert internal_kv.kv_get("cfg") == b"v1"
        # Named actor is reachable again, restarted FRESH (state lost,
        # identity kept) — the GCS actor-restart semantics.
        c2 = ray_tpu.get_actor("counter")
        assert ray_tpu.get(c2.incr.remote(), timeout=60) == 11
        # Idempotent replay: second restore skips the live name.
        again = ha.restore_head_state(snap)
        assert again["named_actors"] == []
    finally:
        ray_tpu.shutdown()


@pytest.mark.chaos
def test_chaos_worker_killer_tasks_still_complete(rt):
    @ray_tpu.remote
    def flaky_sleep(i):
        time.sleep(0.3)
        return i

    killer = ResourceKiller(kind="worker", interval_s=0.25,
                            max_kills=3, seed=0).start()
    try:
        refs = [flaky_sleep.options(max_retries=20).remote(i)
                for i in range(8)]
        assert sorted(ray_tpu.get(refs, timeout=180)) == list(range(8))
    finally:
        kills = killer.stop()
    assert kills >= 1, "chaos never killed anything"


@pytest.mark.chaos
def test_chaos_actor_killer_restarts(rt):
    @ray_tpu.remote
    class Resilient:
        def ping(self):
            return "ok"

    a = Resilient.options(max_restarts=10).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "ok"
    killer = ResourceKiller(kind="actor", interval_s=0.3,
                            max_kills=2, seed=1).start()
    time.sleep(1.0)
    killer.stop()
    # Actor restarted by the control plane; calls succeed again
    # (client-side queueing absorbs the restart window).
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            assert ray_tpu.get(a.ping.remote(), timeout=30) == "ok"
            break
        except Exception:
            time.sleep(0.5)
    else:
        pytest.fail("actor never came back after chaos kills")


def test_head_restore_relinks_placement_group():
    """A named actor living in a placement group must land in the
    RE-RESERVED group after head recovery (old PG ids are dead)."""
    import tempfile
    snap = os.path.join(tempfile.mkdtemp(), "head2.json")
    ray_tpu.init(num_cpus=4)
    try:
        from ray_tpu.core.placement_group import (
            PlacementGroupSchedulingStrategy,
        )
        pg = ray_tpu.placement_group([{"CPU": 1}], strategy="PACK")
        pg.ready(timeout=30)
        NamedCounter.options(
            name="pg_actor",
            scheduling_strategy=PlacementGroupSchedulingStrategy(pg),
        ).remote(0)
        c = ray_tpu.get_actor("pg_actor")
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
        ha.save_head_state(snap)
    finally:
        ray_tpu.shutdown()

    ray_tpu.init(num_cpus=4)
    try:
        restored = ha.restore_head_state(snap)
        assert restored["named_actors"] == ["pg_actor"]
        assert restored["pgs"] == 1
        c2 = ray_tpu.get_actor("pg_actor")
        # Placeable (bound to the new PG) and fresh.
        assert ray_tpu.get(c2.incr.remote(), timeout=60) == 1
    finally:
        ray_tpu.shutdown()


def test_kv_put_if_absent_is_atomic(rt):
    import threading
    wins = []

    def racer(i):
        if internal_kv.kv_put("leader", str(i).encode(),
                              overwrite=False):
            wins.append(i)

    threads = [threading.Thread(target=racer, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
    assert internal_kv.kv_get("leader") == str(wins[0]).encode()
