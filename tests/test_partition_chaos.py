"""Network-partition chaos suite: silent partitions, frame loss, and
corruption injected at the wire layer (core/wire.py ChaosTransport
rules) against every long-lived channel, proving zero task / object /
request loss through the EXISTING recovery paths — reconnect +
dd-replay (client↔head), direct-call seqno replay via the head
(worker↔worker), health-check node failover + task retry
(head↔daemon), and head-relay fallback (object plane).

Reference analog: the chaos ResourceKiller / network-kill release
tests + gRPC keepalive/deadline behavior (SURVEY §4.1, §L1).
"""

from __future__ import annotations

import time
from types import SimpleNamespace

import pytest

import ray_tpu
from ray_tpu.core import wire


# Tight-but-safe knobs: detection must be fast enough to test, slow
# enough that a busy 1-core box's scheduling hiccups never fire a
# false positive on a healthy channel. "Safe" empirically fails on an
# oversubscribed host (tier-1 under driver load: a starved worker went
# >2s silent and its healthy client channel was declared dead), so the
# fixture stretches the deadline by the perf_floor_gate load signal —
# detection-latency asserts scale by the same factor.
HB_INTERVAL = 0.3
HB_TIMEOUT = 2.0


@pytest.fixture
def chaos(tmp_path, monkeypatch):
    """Chaos plan file + cranked liveness knobs, installed BEFORE any
    cluster process starts (daemons/workers inherit both through the
    environment)."""
    from conftest import LOAD_SOFT, host_load_factor
    from ray_tpu.core.config import env_overrides
    path = str(tmp_path / "plan.json")
    wire.write_plan_file(path, [])
    monkeypatch.setenv("RAY_TPU_CHAOS_FILE", path)
    plan = wire.fault_plan()

    def set_rules(rules, settle: float = 0.4):
        wire.write_plan_file(path, rules)
        plan.maybe_refresh(force=True)
        time.sleep(settle)      # remote pollers pick the file up

    t_relax = 4.0 if host_load_factor() > LOAD_SOFT else 1.0
    hb_timeout = HB_TIMEOUT * t_relax
    with env_overrides(heartbeat_interval_s=HB_INTERVAL,
                       heartbeat_timeout_s=hb_timeout,
                       connect_timeout_s=3.0,
                       health_check_period_s=0.25):
        yield SimpleNamespace(path=path, set_rules=set_rules,
                              t_relax=t_relax, hb_timeout=hb_timeout)
    set_rules([], settle=0.0)
    plan.clear()
    plan._file_sig = None


@pytest.fixture
def chaos_rt(chaos):
    ray_tpu.init(num_cpus=4, ignore_reinit_error=False)
    yield chaos
    ray_tpu.shutdown()


def _wait_until(pred, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return time.monotonic()
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# plane: head <-> daemon (node channel)


@pytest.mark.partition
@pytest.mark.chaos
def test_head_daemon_silent_partition_zero_task_loss(chaos):
    """A symmetric silent partition of a daemon node: the head's
    health checker must declare the node dead within its deadline
    (no RST ever arrives — only the missed pongs say so), its tasks
    must retry elsewhere/later instead of hanging, and after the
    partition heals the workload completes with zero loss."""
    from ray_tpu.cluster_utils import Cluster
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 0})
    try:
        node = cluster.add_node(num_cpus=2)
        victim = node.node_id
        rt = ray_tpu.core.api.get_runtime()

        @ray_tpu.remote(num_cpus=1)
        def work(i):
            time.sleep(0.3)
            return i * 2

        refs = [work.remote(i) for i in range(8)]
        time.sleep(0.5)           # let the first wave dispatch
        t0 = time.monotonic()
        chaos.set_rules([wire.FaultRule(
            "freeze", node=victim, direction="both",
            id="sever-node")])
        # Detection: pongs stop silently; threshold trips within
        # period*threshold; allow scheduling slop on a busy box.
        _wait_until(
            lambda: not any(n["NodeID"] == victim and n["Alive"]
                            for n in rt.nodes()),
            timeout=10.0 + 2.5 * chaos.hb_timeout,
            what="node declared dead")
        detect_s = time.monotonic() - t0
        assert detect_s < 10.0 + 2.0 * chaos.hb_timeout, \
            f"detection took {detect_s:.1f}s"
        chaos.set_rules([])       # heal: daemon reconnects, revives
        out = ray_tpu.get(refs, timeout=120)
        assert out == [i * 2 for i in range(8)]
        # The node came back (same identity) once healed.
        _wait_until(
            lambda: any(n["NodeID"] == victim and n["Alive"]
                        for n in rt.nodes()),
            timeout=60.0, what="node re-registered after heal")
    finally:
        chaos.set_rules([], settle=0.0)
        cluster.shutdown()


# ---------------------------------------------------------------------------
# plane: worker <-> worker (direct actor calls)


@pytest.mark.partition
@pytest.mark.chaos
def test_direct_call_one_way_partition_falls_back(chaos_rt):
    """A one-way silent partition of the direct-call plane (caller's
    frames vanish; nothing comes back): the caller's heartbeat
    deadline must kill the channel and the unacked window must replay
    through the head (at-most-once preserved) — every call completes,
    none double-execute."""
    chaos = chaos_rt

    @ray_tpu.remote(num_cpus=0)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self, i):
            self.n += 1
            return i * 3

        def total(self):
            return self.n

    @ray_tpu.remote(num_cpus=1)
    def burst(handle, n, warm):
        rt_c = ray_tpu.core.api.get_runtime()
        # Warm the direct channel (first call head-routes and
        # resolves the lease; the observed get clears the barrier).
        for i in range(warm):
            assert ray_tpu.get(handle.bump.remote(-1 - i),
                               timeout=60) == (-1 - i) * 3
        deadline = time.monotonic() + 20
        while rt_c.actor_calls_direct == 0 \
                and time.monotonic() < deadline:
            ray_tpu.get(handle.bump.remote(-99), timeout=60)
            time.sleep(0.1)
        assert rt_c.actor_calls_direct > 0, "direct path never warmed"
        vals = ray_tpu.get([handle.bump.remote(i) for i in range(n)],
                           timeout=90)
        return vals, rt_c.direct_call_fallbacks

    a = Counter.remote()
    warm = 3
    n = 12
    ref = burst.remote(a, n, warm)
    time.sleep(2.5)               # caller warmed, mid-burst-ish
    chaos.set_rules([wire.FaultRule(
        "freeze", kind="direct", direction="send",
        id="sever-direct-send")])
    time.sleep(chaos.hb_timeout + 1.0)  # detect + fallback window
    chaos.set_rules([])
    vals, fallbacks = ray_tpu.get(ref, timeout=120)
    assert vals == [i * 3 for i in range(n)]
    # Every call executed exactly once (warm + probe retries are
    # bounded below by construction; the n burst adds exactly n).
    total = ray_tpu.get(a.total.remote(), timeout=60)
    assert total >= n + warm


# ---------------------------------------------------------------------------
# plane: client <-> head


@pytest.mark.partition
@pytest.mark.chaos
def test_client_head_partition_reconnect_replay(chaos_rt):
    """Freeze every client channel mid-workload: blocked ops must
    fail over through reconnect + dd-replay once the partition heals
    — every op applies exactly once, nothing hangs."""
    chaos = chaos_rt

    @ray_tpu.remote(num_cpus=1)
    def roundtrips(n):
        got = []
        for i in range(n):
            ref = ray_tpu.put(("v", i))
            got.append(ray_tpu.get(ref, timeout=60))
        return got

    ref = roundtrips.remote(30)
    time.sleep(1.0)               # worker mid-loop
    chaos.set_rules([wire.FaultRule(
        "freeze", kind="client", direction="both",
        id="sever-client")])
    time.sleep(chaos.hb_timeout + 0.5)
    chaos.set_rules([])
    out = ray_tpu.get(ref, timeout=120)
    assert out == [("v", i) for i in range(30)]


# ---------------------------------------------------------------------------
# plane: serve router / replica path


@pytest.mark.partition
@pytest.mark.chaos
def test_serve_partition_zero_request_loss(chaos_rt):
    from ray_tpu import serve
    chaos = chaos_rt

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return {"ok": x["i"]}

    handle = serve.run(Echo.bind())
    try:
        @ray_tpu.remote(num_cpus=1)
        def client(handle, n):
            out = []
            for i in range(n):
                out.append(ray_tpu.get(handle.remote({"i": i}),
                                       timeout=90))
            return out

        ref = client.remote(handle, 20)
        time.sleep(1.5)
        chaos.set_rules([wire.FaultRule(
            "freeze", kind="direct", direction="both",
            id="sever-serve-direct")])
        time.sleep(chaos.hb_timeout + 0.5)
        chaos.set_rules([])
        out = ray_tpu.get(ref, timeout=120)
        assert out == [{"ok": i} for i in range(20)]
    finally:
        serve.shutdown()


# ---------------------------------------------------------------------------
# plane: object transfer (daemon <-> daemon p2p pulls)


@pytest.mark.partition
@pytest.mark.chaos
def test_object_transfer_partition_head_relay_fallback(chaos):
    """Freeze the p2p object plane while a cross-node get is in
    flight: the pull's inactivity deadline must fire (not hang) and
    the head-relay fallback must serve the object DURING the
    partition — zero object loss, no wait for heal."""
    import numpy as np
    from ray_tpu.cluster_utils import Cluster
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 0})
    try:
        cluster.add_node(num_cpus=1, resources={"A": 1})
        cluster.add_node(num_cpus=1, resources={"B": 1})

        @ray_tpu.remote(num_cpus=0, resources={"A": 1})
        def produce():
            return np.arange(500_000, dtype=np.int64)  # ~4 MB

        @ray_tpu.remote(num_cpus=0, resources={"B": 1})
        def consume(arr):
            return int(arr.sum())

        ref = produce.remote()
        ray_tpu.wait([ref], timeout=60)
        chaos.set_rules([wire.FaultRule(
            "freeze", kind="object", direction="both",
            id="sever-object")])
        t0 = time.monotonic()
        out = ray_tpu.get(consume.remote(ref), timeout=90)
        assert out == sum(range(500_000))
        # Served via the relay well inside the partition window —
        # bounded by the pull inactivity deadline, not a hang.
        assert time.monotonic() - t0 < 60
    finally:
        chaos.set_rules([], settle=0.0)
        cluster.shutdown()


# ---------------------------------------------------------------------------
# corruption: checksum -> reset -> retry, visible on the scrape


@pytest.mark.partition
@pytest.mark.chaos
def test_corrupt_frames_reset_and_recover(chaos_rt):
    """Random frame corruption on the client plane: every corrupted
    frame is refused by checksum (never deserialized), surfaces as a
    channel reset, and the workload still completes exactly —
    recovery counters land on the cluster scrape."""
    chaos = chaos_rt
    chaos.set_rules([wire.FaultRule(
        "corrupt", kind="client", direction="send", prob=0.02,
        seed=1234, id="corrupt-client")])

    @ray_tpu.remote(num_cpus=1)
    def roundtrips(n):
        got = []
        for i in range(n):
            got.append(ray_tpu.get(ray_tpu.put(i * 7), timeout=60))
        return got

    out = ray_tpu.get(roundtrips.remote(60), timeout=180)
    chaos.set_rules([])
    assert out == [i * 7 for i in range(60)]
    # Injected-fault/reset counters are registry-visible (the head
    # sees corrupt frames from its clients; worker-side counters ride
    # the exporter the same way).
    rt = ray_tpu.core.api.get_runtime()
    text = rt.observability.prometheus_text()
    assert "ray_tpu_wire_" in text


# ---------------------------------------------------------------------------
# the soak: sustained loss + delay across planes, mixed workload


@pytest.mark.partition
@pytest.mark.chaos
def test_soak_drop_delay_mixed_workload_zero_loss(chaos_rt):
    """1% frame drops + 5% frame delays on the client/direct planes
    (plus delays on node/object) while a task + actor + serve
    workload runs to completion — at-most-once actor calls, exactly
    the expected results, zero losses."""
    from ray_tpu import serve

    # Load-gated deadlines (same signal as conftest.perf_floor_gate):
    # injected delays + retry backoff are timed against wall clock, so
    # on an oversubscribed host the soak finishes late, not lossy —
    # stretch the get() deadlines instead of flaking (tier-1 seed
    # failure under driver load). Correctness asserts are untouched.
    from conftest import LOAD_SOFT, host_load_factor
    t_relax = 4.0 if host_load_factor() > LOAD_SOFT else 1.0
    chaos = chaos_rt

    @serve.deployment
    class Sq:
        def __call__(self, x):
            return x["i"] ** 2

    handle = serve.run(Sq.bind())
    try:
        chaos.set_rules([
            wire.FaultRule("drop", kind="client", direction="both",
                           prob=0.01, seed=11, id="drop-client"),
            wire.FaultRule("drop", kind="direct", direction="both",
                           prob=0.01, seed=12, id="drop-direct"),
            wire.FaultRule("delay", kind="client", direction="send",
                           prob=0.05, delay_s=0.005,
                           delay_jitter_s=0.02, seed=13,
                           id="delay-client"),
            wire.FaultRule("delay", kind="direct", direction="send",
                           prob=0.05, delay_s=0.005,
                           delay_jitter_s=0.02, seed=14,
                           id="delay-direct"),
            wire.FaultRule("delay", kind="node", direction="both",
                           prob=0.05, delay_s=0.005,
                           delay_jitter_s=0.02, seed=15,
                           id="delay-node"),
        ])

        @ray_tpu.remote(num_cpus=1)
        def task(i):
            return i + 1

        @ray_tpu.remote(num_cpus=0)
        class Acc:
            def mul(self, i):
                return i * 3

        @ray_tpu.remote(num_cpus=1)
        def serve_client(handle, n, timeout):
            return [ray_tpu.get(handle.remote({"i": i}),
                                timeout=timeout)
                    for i in range(n)]

        a = Acc.remote()
        task_refs = [task.remote(i) for i in range(40)]
        call_refs = [a.mul.remote(i) for i in range(40)]
        serve_ref = serve_client.remote(handle, 15, 120 * t_relax)
        assert ray_tpu.get(task_refs, timeout=180 * t_relax) == \
            [i + 1 for i in range(40)]
        assert ray_tpu.get(call_refs, timeout=180 * t_relax) == \
            [i * 3 for i in range(40)]
        assert ray_tpu.get(serve_ref, timeout=180 * t_relax) == \
            [i ** 2 for i in range(15)]
    finally:
        chaos.set_rules([], settle=0.0)
        serve.shutdown()
