"""Dataset: read -> transform -> shuffle -> iterate."""

import numpy as np

import ray_tpu
from ray_tpu import data

ray_tpu.init(num_cpus=4)

ds = (data.range(1000)
      .map_batches(lambda b: {"x": [v * 2 for v in b["id"]]})
      .filter(lambda row: row["x"] % 40 == 0)
      .random_shuffle(seed=7))
print("count:", ds.count())
print("take:", ds.take(5))

# feed a training loop in device-ready batches
for batch in ds.iter_batches(batch_size=8):
    arr = np.asarray(batch["x"])
    break
print("first batch:", arr)

ray_tpu.shutdown()
