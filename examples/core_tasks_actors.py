"""Tasks, actors, objects — the core API in one script."""

import ray_tpu

ray_tpu.init(num_cpus=4)

@ray_tpu.remote
def square(x):
    return x * x

@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def incr(self, by=1):
        self.n += by
        return self.n

# tasks fan out; get() gathers
print("squares:", ray_tpu.get([square.remote(i) for i in range(8)]))

# objects: put once, share by reference
big = ray_tpu.put(list(range(10_000)))

@ray_tpu.remote
def head3(xs):
    return xs[:3]

print("head3:", ray_tpu.get(head3.remote(big)))

# actors hold state across calls
c = Counter.remote()
ray_tpu.get([c.incr.remote() for _ in range(10)])
print("count:", ray_tpu.get(c.incr.remote(0)))

# wait: first-completed semantics
refs = [square.remote(i) for i in range(4)]
done, rest = ray_tpu.wait(refs, num_returns=2, timeout=30)
print("done/rest:", len(done), len(rest))

ray_tpu.shutdown()
