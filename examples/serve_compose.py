"""Two composed deployments behind HTTP."""

import json
import urllib.request

import ray_tpu
from ray_tpu import serve

ray_tpu.init(num_cpus=4)

@serve.deployment
class Embedder:
    def __call__(self, text):
        return {"embedding": [len(w) for w in text.split()]}

@serve.deployment(num_replicas=2)
class App:
    def __init__(self, embedder):
        self.embedder = embedder

    async def __call__(self, request):
        if hasattr(request, "json"):
            body = await request.json()
        else:
            body = request
        emb = await self.embedder.remote(body["text"])
        return {"dims": len(emb["embedding"]), **emb}

handle = serve.run(App.bind(Embedder.bind()), http_port=8099)

# direct handle call
print(ray_tpu.get(handle.remote({"text": "hello tpu native serve"})))

# HTTP call
req = urllib.request.Request(
    "http://127.0.0.1:8099/", method="POST",
    data=json.dumps({"text": "over http"}).encode(),
    headers={"Content-Type": "application/json"})
print(json.load(urllib.request.urlopen(req)))

serve.shutdown()
ray_tpu.shutdown()
