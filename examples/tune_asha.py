"""Tuner + ASHA early stopping."""

import ray_tpu
from ray_tpu import tune

ray_tpu.init(num_cpus=4)

def objective(config):
    from ray_tpu.train import report
    acc = 0.0
    for step in range(20):
        acc += config["lr"] * (1.0 - acc)      # toy learning curve
        report({"acc": acc, "step": step})

tuner = tune.Tuner(
    objective,
    param_space={"lr": tune.grid_search([0.01, 0.05, 0.1, 0.3])},
    tune_config=tune.TuneConfig(
        metric="acc", mode="max",
        scheduler=tune.ASHAScheduler(metric="acc", mode="max",
                                     max_t=20)),
)
results = tuner.fit()
best = results.get_best_result(metric="acc", mode="max")
print("best lr:", best.config["lr"], "acc:", round(
    best.metrics["acc"], 4))

ray_tpu.shutdown()
