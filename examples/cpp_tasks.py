"""C++ tasks and actors: native task bodies through the normal API.

The reference's C++ worker API (cpp/include/ray/api.h) lets users
write remote functions in C++. Here: write C++, compile once, call
`.remote()` like any Python task; actor state is a live C++ object
inside the actor's worker process.
"""

import ray_tpu
from ray_tpu import cpp

SRC = r"""
#include "ray_tpu.h"
using raytpu::Args; using raytpu::Bytes;

static Bytes dot(const Args& a) {           // two f64 buffers -> f64
  const double* x = reinterpret_cast<const double*>(a[0].data());
  const double* y = reinterpret_cast<const double*>(a[1].data());
  size_t n = a[0].size() / sizeof(double);
  double s = 0;
  for (size_t i = 0; i < n; ++i) s += x[i] * y[i];
  return raytpu::bytes_of(s);
}
RAY_TPU_TASK(dot);

class RunningMean {
  double sum_ = 0; int64_t n_ = 0;
 public:
  explicit RunningMean(const Args&) {}
  Bytes observe(const Args& a) {
    sum_ += raytpu::as<double>(a[0]); ++n_;
    return raytpu::bytes_of(sum_ / n_);
  }
};
RAY_TPU_ACTOR(RunningMean);
RAY_TPU_METHOD(RunningMean, observe);

RAY_TPU_MODULE();
"""

ray_tpu.init(num_cpus=2)

lib = cpp.load_library(cpp.compile_library(SRC))

import numpy as np
x = np.arange(1000, dtype=np.float64)
ref = lib.dot.remote(x, x)
print("dot(x, x) =", cpp.to_f64(ray_tpu.get(ref)))
assert cpp.to_f64(ray_tpu.get(ref)) == float(x @ x)

Mean = lib.actor_class("RunningMean")
m = Mean.remote()
for v in (1.0, 2.0, 3.0):
    last = m.observe.remote(v)
print("running mean =", cpp.to_f64(ray_tpu.get(last)))

ray_tpu.shutdown()
print("ok")
