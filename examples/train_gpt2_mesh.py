"""Sharded GPT-2 train step on a device mesh.

On a TPU host this uses the real chips; anywhere else, run with a
virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        PYTHONPATH=.. python train_gpt2_mesh.py
"""

import jax
import numpy as np
import optax

from ray_tpu.models import GPT2, GPT2Config
from ray_tpu.models.gpt2 import gpt2_loss_fn
from ray_tpu.parallel import make_mesh
from ray_tpu.train import (
    init_train_state, make_train_step, shard_batch,
)

n_dev = len(jax.devices())
mesh = make_mesh({"dp": n_dev})          # add tp/fsdp/sp axes at will
cfg = GPT2Config.tiny(seq_len=128, vocab_size=512)
model = GPT2(cfg, mesh=mesh)
params = model.init_params(jax.random.key(0))
opt = optax.adamw(3e-4)
state = init_train_state(params, opt, mesh)
step = make_train_step(gpt2_loss_fn(model), opt)

rng = np.random.default_rng(0)
for i in range(5):
    toks = rng.integers(0, cfg.vocab_size,
                        (4 * n_dev, cfg.seq_len)).astype(np.int32)
    batch = shard_batch({"tokens": toks,
                         "targets": np.roll(toks, -1, 1)}, mesh)
    state, metrics = step(state, batch)
    print(f"step {i}: loss={float(metrics['loss']):.4f} "
          f"grad_norm={float(metrics['grad_norm']):.3f}")
