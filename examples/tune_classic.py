"""Classic Tune: class Trainable + callbacks + ExperimentAnalysis."""

import os
import tempfile

import ray_tpu
from ray_tpu import tune

ray_tpu.init(num_cpus=4)


class Quadratic(tune.Trainable):
    """Minimize (x-3)^2 by gradient steps; checkpoints its position."""

    def setup(self, config):
        self.x = 0.0
        self.lr = config["lr"]

    def step(self):
        self.x -= self.lr * 2 * (self.x - 3.0)
        return {"loss": (self.x - 3.0) ** 2,
                "done": self.iteration >= 14}

    def save_checkpoint(self, checkpoint_dir):
        with open(os.path.join(checkpoint_dir, "x.txt"), "w") as f:
            f.write(str(self.x))
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir):
        with open(os.path.join(checkpoint_dir, "x.txt")) as f:
            self.x = float(f.read())


storage = tempfile.mkdtemp()
grid = tune.run(
    Quadratic,
    config={"lr": tune.grid_search([0.05, 0.2, 0.4])},
    storage_path=storage, name="quad",
    progress_reporter=tune.CLIReporter(metric_columns=["loss"],
                                       max_report_frequency=1.0),
)

best = grid.get_best_result("loss", "min")
print("best lr:", best.config["lr"], "loss:", best.metrics["loss"])

# the journal answers the same questions without the Tuner object
ana = tune.ExperimentAnalysis(os.path.join(storage, "quad"))
print("analysis best config:", ana.get_best_config("loss", "min"))
print("best checkpoint dir:", ana.get_best_checkpoint("loss", "min"))

ray_tpu.shutdown()
print("ok")
