"""Durable workflows: events, dynamic continuations, crash resume."""

import os
import tempfile
import time

import ray_tpu
from ray_tpu import workflow

ray_tpu.init(num_cpus=2)
workflow.init(tempfile.mkdtemp())

# -- event step: the workflow parks until the event fires ------------
marker = tempfile.mktemp()


class FileEvent(workflow.EventListener):
    def poll_for_event(self, path):
        while not os.path.exists(path):
            time.sleep(0.05)
        with open(path) as f:
            return f.read()


@ray_tpu.remote
def announce(payload):
    return f"event said: {payload}"


wid = workflow.run_async(
    announce.bind(workflow.wait_for_event(FileEvent, marker)))
print("status while waiting:", workflow.get_status(wid))
with open(marker, "w") as f:
    f.write("go!")
print(workflow.get_output(wid, timeout=60))

# -- dynamic workflow: steps return continuations --------------------


@ray_tpu.remote
def fib(n):
    if n <= 1:
        return n
    return workflow.continuation(add.bind(fib.bind(n - 1),
                                          fib.bind(n - 2)))


@ray_tpu.remote
def add(a, b):
    return a + b


print("fib(9) =", workflow.run(fib.bind(9), workflow_id="fib9",
                               timeout=120))
# completed steps are durable: this resume is a cache read
print("resumed =", workflow.resume("fib9", timeout=60))

ray_tpu.shutdown()
print("ok")
