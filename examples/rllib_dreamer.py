"""Dreamer: world model + imagination-trained actor-critic."""

import numpy as np

import ray_tpu
from ray_tpu.rllib import DreamerConfig


class ChainEnv:
    N = 6

    def __init__(self):
        self.pos = 0
        self.t = 0

    def _obs(self):
        o = np.zeros(self.N, np.float32)
        o[self.pos] = 1.0
        return o

    def reset(self, seed=None):
        self.pos, self.t = 0, 0
        return self._obs(), {}

    def step(self, action):
        self.t += 1
        self.pos = max(0, min(self.N - 1,
                              self.pos + (1 if action == 1 else -1)))
        term = self.pos == self.N - 1
        trunc = self.t >= 20 and not term
        return self._obs(), (1.0 if term else -0.01), term, trunc, {}


ray_tpu.init(num_cpus=4)
algo = (DreamerConfig()
        .environment(ChainEnv, obs_dim=ChainEnv.N, num_actions=2)
        .training(learning_starts=100, wm_updates_per_iter=4)
        .build())
for i in range(6):
    r = algo.train()
    print(f"iter {i}: wm_loss={r.get('wm_loss', float('nan')):.3f} "
          f"imag_return={r.get('imag_return', float('nan')):.3f} "
          f"reward_mean={r['episode_reward_mean']:.3f}")
algo.stop()
ray_tpu.shutdown()
